"""Fake-quantization ops for quantization-aware training (ref
``operators/fake_quantize_op.cc``, ``fake_dequantize_op.cc``; the QAT graph
rewriter lives in ``paddle_tpu.contrib.slim.quantization``).

Quantization model (matching the reference):
    bnt       = 2^(bit_length-1) - 1
    quant(x)  = round(x / scale * bnt)       (stored as float)
    dequant(q)= q * scale / max_range        (max_range = bnt)

``fake_quantize_*`` outputs the integer-valued float tensor + its scale;
``fake_dequantize_*`` maps it back.  The fused
``fake_quantize_dequantize_*`` ops do both and carry a straight-through
estimator gradient (identity inside [-scale, scale], zero outside) so QAT
trains through them — the reference added the fused forms for exactly this
(``fake_quantize_dequantize_moving_average_abs_max``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import grad_var_name
from ..framework.registry import register_op
from .common import X


def _bnt(attrs):
    return float((1 << (int(attrs.get("bit_length", 8)) - 1)) - 1)


def _abs_max(x):
    s = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.maximum(s, 1e-8)


def _channel_abs_max(x, quant_axis=0):
    """Per-channel abs max over every dim except ``quant_axis`` (conv
    filters: axis 0 = out channel; mul/matmul weights: axis 1 = out col)."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    return jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes),
                       1e-8)


def _channel_bshape(ndim, quant_axis):
    shape = [1] * ndim
    shape[quant_axis] = -1
    return tuple(shape)


def _ma_update(state, accum, cur, rate):
    """Shared EMA tracker: state counts decayed updates, accum decayed
    abs-max mass; scale = accum/state (ref fake_quantize_op.cc
    FindMovingAverageAbsMax)."""
    new_state = (rate * state.reshape(()) + 1.0) if state is not None else 1.0
    new_accum = (rate * accum.reshape(()) + cur) if accum is not None else cur
    return new_state, new_accum, new_accum / new_state


def _quant(x, scale, bnt):
    xf = x.astype(jnp.float32)
    return jnp.round(jnp.clip(xf / scale, -1.0, 1.0) * bnt)


# -- plain quantize ops ------------------------------------------------------

@register_op("fake_quantize_abs_max", no_grad=True)
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = X(ins, "X")
    bnt = _bnt(attrs)
    scale = _abs_max(x)
    return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
            "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_abs_max", no_grad=True)
def _fake_channel_wise_quantize_abs_max(ctx, ins, attrs):
    x = X(ins, "X")
    bnt = _bnt(attrs)
    axis = int(attrs.get("quant_axis", 0))
    scales = _channel_abs_max(x, axis)
    out = _quant(x, scales.reshape(_channel_bshape(x.ndim, axis)), bnt)
    return {"Out": [out.astype(x.dtype)], "OutScale": [scales]}


@register_op("fake_quantize_range_abs_max", no_grad=True)
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Scale = windowed max of batch abs-max (ref fake_quantize_op.cc
    FakeQuantizeRangeAbsMaxOp).  With an ``Iter`` counter input the max
    restarts every ``window_size`` steps (a one-slot approximation of the
    reference's scale history window — it recovers from transient spikes
    within one window); without it, the plain running max."""
    x = X(ins, "X")
    in_scale = X(ins, "InScale")
    it = X(ins, "Iter")
    bnt = _bnt(attrs)
    if attrs.get("is_test"):
        scale = in_scale.reshape(())
        return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
                "OutScale": [in_scale.reshape(1)]}
    cur = _abs_max(x)
    if it is not None:
        window = int(attrs.get("window_size", 10000))
        restart = (it.reshape(()).astype(jnp.int32) % window) == 0
        scale = jnp.where(restart, cur,
                          jnp.maximum(cur, in_scale.reshape(())))
        return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
                "OutScale": [scale.reshape(1)],
                "OutIter": [(it + 1).astype(it.dtype)]}
    scale = jnp.maximum(cur, in_scale.reshape(()))
    return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
            "OutScale": [scale.reshape(1)]}


def _ma_outs(state, accum, new_state, new_accum):
    outs = {}
    if state is not None:
        outs["OutState"] = [jnp.reshape(new_state, (1,))]
    if accum is not None:
        outs["OutAccum"] = [jnp.reshape(new_accum, (1,))]
    return outs


@register_op("fake_quantize_moving_average_abs_max", no_grad=True)
def _fake_quantize_moving_average_abs_max(ctx, ins, attrs):
    x = X(ins, "X")
    in_scale = X(ins, "InScale")
    state = X(ins, "InState")
    accum = X(ins, "InAccum")
    bnt = _bnt(attrs)
    if attrs.get("is_test"):
        scale = in_scale.reshape(())
        return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
                "OutScale": [in_scale.reshape(1)]}
    new_state, new_accum, scale = _ma_update(
        state, accum, _abs_max(x), attrs.get("moving_rate", 0.9))
    return {"Out": [_quant(x, scale, bnt).astype(x.dtype)],
            "OutScale": [scale.reshape(1)],
            **_ma_outs(state, accum, new_state, new_accum)}


@register_op("moving_average_abs_max_scale", no_grad=True)
def _moving_average_abs_max_scale(ctx, ins, attrs):
    """Track the scale only; Out passes X through (ref
    moving_average_abs_max_scale op used for output-scale collection)."""
    x = X(ins, "X")
    state = X(ins, "InState")
    accum = X(ins, "InAccum")
    if attrs.get("is_test"):
        # frozen: report the trained scale without touching the trackers
        if accum is not None and state is not None:
            scale = accum.reshape(()) / jnp.maximum(state.reshape(()), 1e-8)
        else:
            scale = _abs_max(x)
        return {"Out": [x], "OutScale": [scale.reshape(1)]}
    new_state, new_accum, scale = _ma_update(
        state, accum, _abs_max(x), attrs.get("moving_rate", 0.9))
    return {"Out": [x], "OutScale": [scale.reshape(1)],
            **_ma_outs(state, accum, new_state, new_accum)}


# -- dequantize --------------------------------------------------------------

@register_op("fake_dequantize_max_abs", no_grad=True)
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x, scale = X(ins, "X"), X(ins, "Scale")
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [(x.astype(jnp.float32) * scale.reshape(()) /
                     max_range).astype(x.dtype)]}


@register_op("fake_channel_wise_dequantize_max_abs", no_grad=True)
def _fake_channel_wise_dequantize_max_abs(ctx, ins, attrs):
    xs = ins.get("X", [])
    scales = ins.get("Scales", [])
    x = xs[0]
    bits = attrs.get("quant_bits", [8])
    bnt0 = float((1 << (int(bits[0]) - 1)) - 1)
    s0 = scales[0]
    bshape = (-1,) + (1,) * (x.ndim - 1)
    out = x.astype(jnp.float32) * s0.reshape(bshape) / bnt0
    if len(scales) > 1 and scales[1] is not None and len(bits) > 1:
        bnt1 = float((1 << (int(bits[1]) - 1)) - 1)
        out = out * scales[1].reshape(()) / bnt1
    return {"Out": [out.astype(x.dtype)]}


# -- fused quant-dequant with STE gradient (the QAT workhorses) --------------

def _qdq(x, scale, bnt):
    return _quant(x, scale, bnt) * scale / bnt


def _qdq_grad_maker(op, block, no_grad_set):
    g_inputs = {"X": op.input("X"),
                "OutScale": op.output("OutScale"),
                "OutGrad": [grad_var_name(n) for n in op.output("Out")]}
    g_outputs = {"XGrad": [grad_var_name(n) for n in op.input("X")]}
    return [{"type": "fake_quantize_dequantize_grad", "inputs": g_inputs,
             "outputs": g_outputs, "attrs": dict(op.attrs)}]


@register_op("fake_quantize_dequantize_grad")
def _fake_quantize_dequantize_grad(ctx, ins, attrs):
    """Straight-through estimator: identity inside [-scale, scale], zero
    outside (values beyond the clip range got a flat output)."""
    x, gout = X(ins, "X"), X(ins, "OutGrad")
    raw = X(ins, "OutScale")
    if raw.size > 1:
        axis = int(attrs.get("quant_axis", 0))
        scale = raw.reshape(_channel_bshape(x.ndim, axis))
    else:
        scale = raw.reshape(())
    inside = (jnp.abs(x.astype(jnp.float32)) <= scale).astype(gout.dtype)
    return {"XGrad": [gout * inside]}


def _register_qdq(name, scale_fn, channel=False):
    def lower(ctx, ins, attrs):
        x = X(ins, "X")
        bnt = _bnt(attrs)
        outs = scale_fn(ctx, ins, attrs, x)
        scale = outs.pop("__scale__")
        if channel:
            axis = int(attrs.get("quant_axis", 0))
            out = _qdq(x.astype(jnp.float32),
                       scale.reshape(_channel_bshape(x.ndim, axis)), bnt)
        else:
            out = _qdq(x.astype(jnp.float32), scale, bnt)
        outs["Out"] = [out.astype(x.dtype)]
        return outs
    register_op(name, lower, grad_maker=_qdq_grad_maker)


def _scale_abs_max(ctx, ins, attrs, x):
    s = _abs_max(x)
    return {"__scale__": s, "OutScale": [s.reshape(1)]}


def _scale_channel(ctx, ins, attrs, x):
    s = _channel_abs_max(x, int(attrs.get("quant_axis", 0)))
    return {"__scale__": s, "OutScale": [s]}


def _scale_moving_average(ctx, ins, attrs, x):
    in_scale = X(ins, "InScale")
    state = X(ins, "InState")
    accum = X(ins, "InAccum")
    if attrs.get("is_test"):
        s = in_scale.reshape(())
        return {"__scale__": s, "OutScale": [in_scale.reshape(1)]}
    new_state, new_accum, s = _ma_update(
        state, accum, _abs_max(x), attrs.get("moving_rate", 0.9))
    return {"__scale__": s, "OutScale": [s.reshape(1)],
            **_ma_outs(state, accum, new_state, new_accum)}


_register_qdq("fake_quantize_dequantize_abs_max", _scale_abs_max)
_register_qdq("fake_channel_wise_quantize_dequantize_abs_max",
              _scale_channel, channel=True)
_register_qdq("fake_quantize_dequantize_moving_average_abs_max",
              _scale_moving_average)
