"""Reduction op lowerings (ref ``operators/reduce_ops/`` — 29 files)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.registry import register_op
from .common import X, reduce_axes

_REDUCE = {
    "reduce_sum": jnp.sum,
    "reduce_mean": jnp.mean,
    "reduce_max": jnp.max,
    "reduce_min": jnp.min,
    "reduce_prod": jnp.prod,
}


def _make_reduce(name, fn):
    def lower(ctx, ins, attrs):
        x = X(ins, "X")
        axes = reduce_axes(attrs.get("dim"), x.ndim, attrs.get("reduce_all", False))
        out = fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        return {"Out": [out]}
    register_op(name, lower)


for _n, _f in _REDUCE.items():
    _make_reduce(_n, _f)

for _n, _f in {"reduce_all": jnp.all, "reduce_any": jnp.any}.items():
    def _mk(fn):
        def lower(ctx, ins, attrs):
            x = X(ins, "X")
            axes = reduce_axes(attrs.get("dim"), x.ndim,
                               attrs.get("reduce_all", False))
            return {"Out": [fn(x, axis=axes,
                               keepdims=attrs.get("keep_dim", False))]}
        return lower
    register_op(_n, _mk(_f), no_grad=True)


@register_op("logsumexp")
def _logsumexp(ctx, ins, attrs):
    import jax
    x = X(ins, "X")
    axes = reduce_axes(attrs.get("dim"), x.ndim, attrs.get("reduce_all", False))
    return {"Out": [jax.scipy.special.logsumexp(
        x, axis=axes, keepdims=attrs.get("keep_dim", False))]}


@register_op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(X(ins, "X"))]}


@register_op("max")
def _max(ctx, ins, attrs):
    return {"Out": [jnp.max(X(ins, "X"))]}
