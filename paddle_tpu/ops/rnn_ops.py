"""Recurrent op lowerings: LSTM / GRU as lax.scan time loops.

ref ``operators/lstm_op.cc``, ``operators/gru_op.cc``, ``operators/
cudnn_lstm_op.cu`` and the sequence2batch machinery
(``operators/math/sequence2batch.h``).  TPU-native form: dense padded
[batch, time, ...] activations, one lax.scan over time, gate matmuls batched
onto the MXU; padding steps are masked by the SeqLen companion so results
match LoD semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import X


def _act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda x: x}[name]


@register_op("lstm")
def _lstm(ctx, ins, attrs):
    """Inputs: Input [b,t,4d] (pre-projected x·W), Weight [d,4d] (recurrent),
    Bias [1,4d or 1,7d w/ peepholes], optional H0/C0, SeqLen.
    Gate order i,f,c,o (ref operators/math/detail/lstm_kernel.h)."""
    x = X(ins, "Input")
    w = X(ins, "Weight")
    bias = X(ins, "Bias")
    h0, c0 = X(ins, "H0"), X(ins, "C0")
    seq_len = X(ins, "SeqLen")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    use_peepholes = attrs.get("use_peepholes", False)
    b, t, d4 = x.shape
    d = d4 // 4
    if h0 is None:
        h0 = jnp.zeros((b, d), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, d), x.dtype)
    if bias is not None:
        gate_bias = bias.reshape(-1)[:4 * d]
        x = x + gate_bias
        if use_peepholes:
            peep = bias.reshape(-1)[4 * d:]
            w_ic, w_fc, w_oc = peep[:d], peep[d:2 * d], peep[2 * d:3 * d]
    mask = None
    if seq_len is not None:
        mask = (jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)).astype(x.dtype)

    def step(carry, inp):
        h, c = carry
        xt, mt = inp
        gates = xt + h @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        cand = cand_act(gc)
        c_new = f * c + i * cand
        if use_peepholes:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        if mt is not None:
            m = mt[:, None]
            h_new = h_new * m + h * (1 - m)
            c_new = c_new * m + c * (1 - m)
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else jnp.ones((t, b), x.dtype)
    (h_f, c_f), (hs, cs) = jax.lax.scan(
        step, (h0, c0), (xs, ms), reverse=attrs.get("is_reverse", False))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {"Hidden": [hidden], "Cell": [cell],
            "BatchGate": [x], "BatchCellPreAct": [cell],
            "LastH": [h_f], "LastC": [c_f]}


@register_op("gru")
def _gru(ctx, ins, attrs):
    """Inputs: Input [b,t,3d] (x·W pre-projection), Weight [d,3d]
    (layout: [d,2d] update/reset | [d,d] candidate — ref gru_op.cc), Bias
    [1,3d], optional H0, SeqLen.  Gate order u,r,c."""
    x = X(ins, "Input")
    w = X(ins, "Weight")
    bias = X(ins, "Bias")
    h0 = X(ins, "H0")
    seq_len = X(ins, "SeqLen")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    origin_mode = attrs.get("origin_mode", False)
    b, t, d3 = x.shape
    d = d3 // 3
    w_ur = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    if bias is not None:
        x = x + bias.reshape(-1)
    if h0 is None:
        h0 = jnp.zeros((b, d), x.dtype)
    mask = None
    if seq_len is not None:
        mask = (jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)).astype(x.dtype)

    def step(h, inp):
        xt, mt = inp
        xu, xr, xc = xt[:, :d], xt[:, d:2 * d], xt[:, 2 * d:]
        ur = gate_act(jnp.concatenate([xu, xr], -1) + h @ w_ur)
        u, r = ur[:, :d], ur[:, d:]
        c = cand_act(xc + (r * h) @ w_c)
        if origin_mode:
            h_new = u * h + (1 - u) * c
        else:
            h_new = (1 - u) * h + u * c
        if mt is not None:
            m = mt[:, None]
            h_new = h_new * m + h * (1 - m)
        return h_new, h_new

    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else jnp.ones((t, b), x.dtype)
    h_f, hs = jax.lax.scan(step, h0, (xs, ms),
                           reverse=attrs.get("is_reverse", False))
    hidden = jnp.swapaxes(hs, 0, 1)
    return {"Hidden": [hidden], "BatchGate": [x],
            "BatchResetHiddenPrev": [hidden], "BatchHidden": [hidden],
            "LastH": [h_f]}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (ref gru_unit_op.cc)."""
    inp = X(ins, "Input")       # [b, 3d]
    h_prev = X(ins, "HiddenPrev")
    w = X(ins, "Weight")
    bias = X(ins, "Bias")
    d = h_prev.shape[-1]
    gate_act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        attrs.get("gate_activation", 1), "sigmoid")
        if isinstance(attrs.get("gate_activation", 1), int)
        else attrs.get("gate_activation"))
    cand_act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        attrs.get("activation", 2), "tanh")
        if isinstance(attrs.get("activation", 2), int)
        else attrs.get("activation"))
    x = inp + (bias.reshape(-1) if bias is not None else 0.0)
    w_ur = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
    gates = jnp.concatenate([xu, xr], -1) + h_prev @ w_ur
    u, r = gate_act(gates[:, :d]), gate_act(gates[:, d:])
    c = cand_act(xc + (r * h_prev) @ w_c)
    h = u * c + (1 - u) * h_prev
    return {"Gate": [jnp.concatenate([u, r, c], -1)],
            "ResetHiddenPrev": [r * h_prev], "Hidden": [h]}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    x = X(ins, "X")   # [b, 4d]
    c_prev = X(ins, "C_prev")
    forget_bias = attrs.get("forget_bias", 0.0)
    d = c_prev.shape[-1]
    i, j, f, o = jnp.split(x, 4, axis=-1)
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    return {"C": [c], "H": [h]}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (ref row_conv_op.cc) on [b,t,d]."""
    x, filt = X(ins, "X"), X(ins, "Filter")
    ctx_len = filt.shape[0]
    pads = jnp.pad(x, [(0, 0), (0, ctx_len - 1), (0, 0)])
    out = sum(pads[:, i:i + x.shape[1]] * filt[i] for i in range(ctx_len))
    return {"Out": [out]}
