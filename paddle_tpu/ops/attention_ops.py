"""Fused attention ops backed by the Pallas kernels.

The reference builds attention from separate matmul/softmax/dropout ops
(``tests/unittests/dist_transformer.py:1034``); these ops fuse the whole
pattern so the [b, h, T, T] score matrix never reaches HBM.

- ``flash_attention``: single-device fused attention (Pallas on TPU).
- ``ring_attention``: the same contract, but when the active mesh has an
  ``sp`` axis the sequence dimension is sharded and KV shards rotate over
  the ring (``paddle_tpu.pallas.ring_attention``); without an sp axis it
  degrades to flash attention, so programs are portable across meshes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import X


@register_op("flash_attention")
def _flash_attention(ctx, ins, attrs):
    from ..pallas import flash_attention
    q, k, v = X(ins, "Q"), X(ins, "K"), X(ins, "V")
    bias = X(ins, "Bias")
    bq, bk = attrs.get("block_q"), attrs.get("block_k")
    out = flash_attention(
        q, k, v, bias=bias, causal=bool(attrs.get("causal", False)),
        sm_scale=attrs.get("sm_scale") or None,
        block_q=int(bq) if bq else None,     # None → kernel's tuned default
        block_k=int(bk) if bk else None,
        bwd_impl=attrs.get("bwd_impl") or None)
    return {"Out": [out]}


@register_op("ring_attention")
def _ring_attention(ctx, ins, attrs):
    from ..parallel.mesh import current_mesh
    q, k, v = X(ins, "Q"), X(ins, "K"), X(ins, "V")
    causal = bool(attrs.get("causal", False))
    sm_scale = attrs.get("sm_scale") or None
    axis = attrs.get("axis_name", "sp") or "sp"

    mesh = current_mesh()
    if mesh is not None and axis in mesh.axis_names and \
            mesh.shape[axis] > 1:
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:  # pragma: no cover
            from jax.experimental.shard_map import shard_map
        from ..pallas import ring_attention as _ring
        spec = P(None, None, axis, None)
        fn = shard_map(
            lambda q_, k_, v_: _ring(q_, k_, v_, axis, causal=causal,
                                     sm_scale=sm_scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return {"Out": [fn(q, k, v)]}

    from ..pallas import flash_attention
    return {"Out": [flash_attention(q, k, v, causal=causal,
                                    sm_scale=sm_scale)]}
