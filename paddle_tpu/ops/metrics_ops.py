"""Metric op lowerings (ref ``operators/metrics/``: accuracy, auc,
precision_recall)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.registry import register_op
from .common import X


@register_op("accuracy", no_grad=True)
def _accuracy(ctx, ins, attrs):
    """ref operators/metrics/accuracy_op.cc — Out: [topk] indices vs label."""
    indices, label = X(ins, "Indices"), X(ins, "Label")
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    correct = jnp.any(indices == label[:, None].astype(indices.dtype), axis=1)
    n = indices.shape[0]
    num_correct = jnp.sum(correct.astype(jnp.float32))
    return {"Accuracy": [num_correct / n],
            "Correct": [num_correct.astype(jnp.int32)],
            "Total": [jnp.asarray(n, jnp.int32)]}


@register_op("auc", no_grad=True)
def _auc(ctx, ins, attrs):
    """Streaming AUC with histogram stat buffers (ref metrics/auc_op.cc)."""
    predict, label = X(ins, "Predict"), X(ins, "Label")
    stat_pos, stat_neg = X(ins, "StatPos"), X(ins, "StatNeg")
    num_thresh = attrs.get("num_thresholds", 4095)
    pos_score = predict[:, 1] if predict.ndim == 2 and predict.shape[1] > 1 \
        else predict.reshape(-1)
    lab = label.reshape(-1).astype(jnp.float32)
    bins = jnp.clip((pos_score * num_thresh).astype(jnp.int32), 0, num_thresh)
    sp = stat_pos.reshape(-1).at[bins].add(lab)
    sn = stat_neg.reshape(-1).at[bins].add(1.0 - lab)
    # trapezoid sum over thresholds, descending
    tp = jnp.cumsum(sp[::-1])
    fp = jnp.cumsum(sn[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg), 0.0)
    return {"AUC": [auc], "StatPosOut": [sp.reshape(stat_pos.shape)],
            "StatNegOut": [sn.reshape(stat_neg.shape)]}


@register_op("precision_recall", no_grad=True)
def _precision_recall(ctx, ins, attrs):
    indices, labels = X(ins, "Indices"), X(ins, "Labels")
    states = X(ins, "StatesInfo")
    cls = attrs["class_number"]
    pred = indices.reshape(-1).astype(jnp.int32)
    lab = labels.reshape(-1).astype(jnp.int32)
    tp = jnp.zeros((cls,), jnp.float32).at[lab].add((pred == lab).astype(jnp.float32))
    fp = jnp.zeros((cls,), jnp.float32).at[pred].add((pred != lab).astype(jnp.float32))
    fn = jnp.zeros((cls,), jnp.float32).at[lab].add((pred != lab).astype(jnp.float32))
    batch_states = jnp.stack([tp, fp, jnp.zeros_like(tp), fn], axis=1)
    acc_states = batch_states + (states if states is not None else 0.0)

    def metrics(st):
        tp_, fp_, _, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12), 0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        tps, fps, fns = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mprec = jnp.where(tps + fps > 0, tps / (tps + fps + 1e-12), 0.0)
        mrec = jnp.where(tps + fns > 0, tps / (tps + fns + 1e-12), 0.0)
        mf1 = jnp.where(mprec + mrec > 0,
                        2 * mprec * mrec / (mprec + mrec + 1e-12), 0.0)
        micro = jnp.stack([mprec, mrec, mf1])
        return jnp.concatenate([macro, micro])

    return {"BatchMetrics": [metrics(batch_states)],
            "AccumMetrics": [metrics(acc_states)],
            "AccumStatesInfo": [acc_states]}


@register_op("mean_iou", no_grad=True)
def _mean_iou(ctx, ins, attrs):
    pred, label = X(ins, "Predictions"), X(ins, "Labels")
    n = attrs["num_classes"]
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    inter = jnp.zeros((n,), jnp.float32).at[l].add((p == l).astype(jnp.float32))
    area_p = jnp.zeros((n,), jnp.float32).at[p].add(1.0)
    area_l = jnp.zeros((n,), jnp.float32).at[l].add(1.0)
    union = area_p + area_l - inter
    iou = jnp.where(union > 0, inter / (union + 1e-12), 0.0)
    valid = (union > 0).astype(jnp.float32)
    mean_iou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1.0)
    return {"OutMeanIou": [mean_iou], "OutWrong": [(union - inter).astype(jnp.int32)],
            "OutCorrect": [inter.astype(jnp.int32)]}


@register_op("chunk_eval", no_grad=True)
def _chunk_eval(ctx, ins, attrs):
    raise NotImplementedError(
        "chunk_eval requires host-side chunk parsing; use "
        "paddle_tpu.metrics.ChunkEvaluator on fetched numpy outputs")
