"""Long-tail op-surface parity (SURVEY Appendix A stragglers).

Each lowering cites its reference kernel.  Ops whose reference semantics
depend on dynamic shapes (LoD splits, id sharding) are realized in the
dense-masked form the rest of this framework uses for ragged data (SURVEY
§5.7): same information, static shapes, documented per op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import registry
from ..framework.registry import register_op
from .common import X, XS, ids_dtype, canon_dtype


def alias_op(new: str, old: str) -> None:
    """Register ``new`` as an exact alias of an existing lowering."""
    info = registry.get_op_info(old)
    register_op(new, info.lower, infer=info.infer,
                grad_maker=info.grad_maker, no_grad=info.no_grad,
                stateful_rng=info.stateful_rng, raw=info.raw)


# -- straight aliases (same kernel, alternate registered name) ---------------
# ref: write_to_array/read_from_array (operators/tensor_array_read_write_op
# .cc), lod_array_length, conditional_block_infer (controlflow/
# conditional_block_infer_op.cc), multiclass_nms2 (adds RoisNum — identical
# math), split_byref (split without copy; XLA is SSA anyway)
alias_op("write_to_array", "array_write")
alias_op("read_from_array", "array_read")
alias_op("lod_array_length", "array_length")
alias_op("conditional_block_infer", "conditional_block")
alias_op("multiclass_nms2", "multiclass_nms")
alias_op("split_byref", "split")
alias_op("fill_zeros_like2", "fill_zeros_like")


@register_op("fill", no_grad=True)
def _fill(ctx, ins, attrs):
    """ref operators/fill_op.cc: constant tensor from a value list attr."""
    shape = attrs["shape"]
    value = np.asarray(attrs["value"], np.float64).reshape(shape)
    return {"Out": [jnp.asarray(value, canon_dtype(
        attrs.get("dtype", "float32")))]}


def _batch_size_like_shape(ins, attrs):
    ref = X(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    return shape


@register_op("uniform_random_batch_size_like", no_grad=True,
             stateful_rng=True)
def _uniform_random_batch_size_like(ctx, ins, attrs):
    """ref operators/uniform_random_batch_size_like_op.cc."""
    shape = _batch_size_like_shape(ins, attrs)
    u = jax.random.uniform(ctx.rng(), tuple(shape),
                           minval=attrs.get("min", -1.0),
                           maxval=attrs.get("max", 1.0))
    return {"Out": [u.astype(canon_dtype(attrs.get("dtype", "float32")))]}


@register_op("gaussian_random_batch_size_like", no_grad=True,
             stateful_rng=True)
def _gaussian_random_batch_size_like(ctx, ins, attrs):
    shape = _batch_size_like_shape(ins, attrs)
    g = jax.random.normal(ctx.rng(), tuple(shape)) * \
        attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": [g.astype(canon_dtype(attrs.get("dtype", "float32")))]}


# -- losses / simple math ----------------------------------------------------

@register_op("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    """ref operators/modified_huber_loss_op.cc: y∈{0,1} mapped to ±1;
    quadratic inside margin, linear outside."""
    x, y = X(ins, "X"), X(ins, "Y")
    target = 2.0 * y.astype(jnp.float32) - 1.0
    z = x * target
    inter = jnp.square(jnp.maximum(1.0 - z, 0.0))
    loss = jnp.where(z < -1.0, -4.0 * z, inter)
    return {"IntermediateVal": [z], "Out": [loss]}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    """ref operators/squared_l2_distance_op.cc: row-wise ||x-y||²."""
    x, y = X(ins, "X"), X(ins, "Y")
    sub = x - y
    return {"sub_result": [sub],
            "Out": [jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                            keepdims=sub.ndim > 1)]}


@register_op("positive_negative_pair", no_grad=True)
def _positive_negative_pair(ctx, ins, attrs):
    """ref operators/positive_negative_pair_op.cc: within each query id,
    count score pairs ordered agreeing/disagreeing with the labels."""
    score = X(ins, "Score").reshape(-1)
    label = X(ins, "Label").reshape(-1)
    qid = X(ins, "QueryID").reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)
    valid = same_q & (upper > 0)
    ds = score[:, None] - score[None, :]
    dl = label[:, None] - label[None, :]
    informative = valid & (dl != 0)
    pos = jnp.sum(informative & (ds * dl > 0)).astype(jnp.float32)
    neg = jnp.sum(informative & (ds * dl < 0)).astype(jnp.float32)
    neu = jnp.sum(informative & (ds == 0)).astype(jnp.float32)
    acc_pos = X(ins, "AccumulatePositivePair")
    acc_neg = X(ins, "AccumulateNegativePair")
    acc_neu = X(ins, "AccumulateNeutralPair")
    if acc_pos is not None:
        pos = pos + acc_pos.reshape(())
        neg = neg + acc_neg.reshape(())
        neu = neu + acc_neu.reshape(())
    return {"PositivePair": [pos.reshape(1)],
            "NegativePair": [neg.reshape(1)],
            "NeutralPair": [neu.reshape(1)]}


@register_op("cvm")
def _cvm(ctx, ins, attrs):
    """ref operators/cvm_op.cc: first two cols are (show, click) counters;
    use_cvm keeps them log-transformed, else strips them."""
    x = X(ins, "X")
    show = jnp.log(x[:, 0:1] + 1.0)
    ctr = jnp.log(x[:, 1:2] + 1.0) - show
    if attrs.get("use_cvm", True):
        return {"Y": [jnp.concatenate([show, ctr, x[:, 2:]], axis=1)]}
    return {"Y": [x[:, 2:]]}


@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """ref operators/conv_shift_op.cc: per-row circular correlation,
    y width M (odd) centred on each position."""
    x, y = X(ins, "X"), X(ins, "Y")
    m = y.shape[1]
    half = m // 2
    out = jnp.zeros_like(x)
    for j in range(m):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return {"Out": [out]}


# -- int8 scale ops (ref operators/mkldnn quantize/dequantize/requantize) ----

@register_op("quantize", no_grad=True)
def _quantize(ctx, ins, attrs):
    x = X(ins, "Input")
    s = attrs.get("Scale", 1.0)
    out = jnp.clip(jnp.round(x.astype(jnp.float32) * s), -128, 127)
    return {"Output": [out.astype(jnp.int8)]}


@register_op("dequantize", no_grad=True)
def _dequantize(ctx, ins, attrs):
    x = X(ins, "Input")
    s = attrs.get("Scale", 1.0)
    return {"Output": [x.astype(jnp.float32) / s]}


@register_op("requantize", no_grad=True)
def _requantize(ctx, ins, attrs):
    x = X(ins, "Input")
    s_in = attrs.get("Scale_in", 1.0)
    s_out = attrs.get("Scale_out", 1.0)
    out = jnp.clip(jnp.round(x.astype(jnp.float32) / s_in * s_out),
                   -128, 127)
    return {"Output": [out.astype(jnp.int8)]}


# -- pooling with argmax index, unpool, spp ----------------------------------

def _windows(x, kh, kw, sh, sw, ph, pw):
    """Stack the kh·kw shifted strided views: [n, c, oh, ow, kh*kw]."""
    n, c, h, w = x.shape
    xpad = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                   constant_values=-jnp.inf)
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    views = []
    for i in range(kh):
        for j in range(kw):
            views.append(jax.lax.slice(
                xpad, (0, 0, i, j),
                (n, c, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
    return jnp.stack(views, axis=-1), oh, ow


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """ref operators/pool_with_index_op.cc: max pool + flat h*w argmax."""
    x = X(ins, "X")
    kh, kw = attrs["ksize"]
    sh, sw = attrs.get("strides", [1, 1])
    ph, pw = attrs.get("paddings", [0, 0])
    n, c, h, w = x.shape
    win, oh, ow = _windows(x, kh, kw, sh, sw, ph, pw)
    out = jnp.max(win, axis=-1)
    arg = jnp.argmax(win, axis=-1)                    # in-window index
    ki, kj = arg // kw, arg % kw
    rows = (jnp.arange(oh) * sh)[None, None, :, None] + ki - ph
    cols = (jnp.arange(ow) * sw)[None, None, None, :] + kj - pw
    mask = jnp.clip(rows, 0, h - 1) * w + jnp.clip(cols, 0, w - 1)
    return {"Out": [out], "Mask": [mask.astype(jnp.int32)]}


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    """3-D variant via one depth loop over the 2-D kernel."""
    x = X(ins, "X")
    kd, kh, kw = attrs["ksize"]
    sd, sh, sw = attrs.get("strides", [1, 1, 1])
    pd, ph, pw = attrs.get("paddings", [0, 0, 0])
    n, c, d, h, w = x.shape
    outs, masks = [], []
    od = (d + 2 * pd - kd) // sd + 1
    xpad = jnp.pad(x, ((0, 0), (0, 0), (pd, pd), (0, 0), (0, 0)),
                   constant_values=-jnp.inf)
    for oz in range(od):
        slabs, slab_masks = [], []
        for dz in range(kd):
            z = oz * sd + dz
            win, oh, ow = _windows(xpad[:, :, z], kh, kw, sh, sw, ph, pw)
            m = jnp.max(win, axis=-1)
            a = jnp.argmax(win, axis=-1)
            ki, kj = a // kw, a % kw
            rows = (jnp.arange(oh) * sh)[None, None, :, None] + ki - ph
            cols = (jnp.arange(ow) * sw)[None, None, None, :] + kj - pw
            flat = ((z - pd) * h * w + jnp.clip(rows, 0, h - 1) * w +
                    jnp.clip(cols, 0, w - 1))
            slabs.append(m)
            slab_masks.append(flat)
        stack = jnp.stack(slabs, axis=-1)
        best = jnp.argmax(stack, axis=-1)
        outs.append(jnp.max(stack, axis=-1))
        masks.append(jnp.take_along_axis(
            jnp.stack(slab_masks, axis=-1), best[..., None], -1)[..., 0])
    return {"Out": [jnp.stack(outs, axis=2)],
            "Mask": [jnp.stack(masks, axis=2).astype(jnp.int32)]}


@register_op("unpool")
def _unpool(ctx, ins, attrs):
    """ref operators/unpool_op.cc: scatter pooled values to their argmax
    positions in the unpooled [h, w] plane."""
    x, idx = X(ins, "X"), X(ins, "Indices")
    oh, ow = attrs["unpooled_height"], attrs["unpooled_width"]
    n, c = x.shape[:2]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1))
    return {"Out": [out.reshape(n, c, oh, ow)]}


@register_op("spp")
def _spp(ctx, ins, attrs):
    """ref operators/spp_op.cc: pyramid of adaptive pools, flattened."""
    x = X(ins, "X")
    n, c, h, w = x.shape
    levels = attrs.get("pyramid_height", 3)
    ptype = attrs.get("pooling_type", "max")
    red = jnp.max if ptype == "max" else jnp.mean
    feats = []
    for lv in range(levels):
        bins = 2 ** lv
        # pad to a multiple then reshape-reduce (adaptive pooling)
        hh = -(-h // bins) * bins
        ww = -(-w // bins) * bins
        pad_val = -jnp.inf if ptype == "max" else 0.0
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, hh - h), (0, ww - w)),
                     constant_values=pad_val)
        r = red(xp.reshape(n, c, bins, hh // bins, bins, ww // bins),
                axis=(3, 5))
        if ptype == "avg":
            # renormalize for the zero padding
            ones = jnp.pad(jnp.ones((1, 1, h, w)),
                           ((0, 0), (0, 0), (0, hh - h), (0, ww - w)))
            cnt = jnp.mean(ones.reshape(1, 1, bins, hh // bins, bins,
                                        ww // bins), axis=(3, 5))
            r = r / jnp.maximum(cnt, 1e-8)
        feats.append(r.reshape(n, -1))
    return {"Out": [jnp.concatenate(feats, axis=1)]}


# -- dense LoD-machinery equivalents (SURVEY §5.7: lengths replace LoD) ------

@register_op("lod_reset")
def _lod_reset(ctx, ins, attrs):
    """ref operators/lod_reset_op.cc — LoD is metadata-only here (dense
    batches + length companions), so the values pass through."""
    return {"Out": [X(ins, "X")]}


@register_op("lod_rank_table", no_grad=True)
def _lod_rank_table(ctx, ins, attrs):
    """ref lod_rank_table_op.cc: (index, length) sorted by length desc.
    Dense form: input is the LENGTHS vector (the LoD companion)."""
    lengths = X(ins, "X").reshape(-1).astype(jnp.int32)
    order = jnp.argsort(-lengths, stable=True)
    return {"Out": [jnp.stack([order.astype(jnp.int32), lengths[order]],
                              axis=1)]}


@register_op("max_sequence_len", no_grad=True)
def _max_sequence_len(ctx, ins, attrs):
    """ref max_sequence_len_op.cc: longest length in a rank table."""
    table = X(ins, "RankTable")
    return {"Out": [jnp.max(table[:, 1]).astype(ids_dtype()).reshape(())]}


@register_op("reorder_lod_tensor_by_rank")
def _reorder_lod_tensor_by_rank(ctx, ins, attrs):
    """ref reorder_lod_tensor_by_rank_op.cc: permute batch rows into rank
    -table order (dense: gather on dim 0)."""
    x = X(ins, "X")
    table = X(ins, "RankTable")
    order = table[:, 0].astype(jnp.int32)
    return {"Out": [x[order]]}


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ctx, ins, attrs):
    """ref shrink_rnn_memory_op.cc: keep the first k rows (sequences still
    alive at this step).  Dense scans mask instead of shrinking, so k rows
    are kept in place and the rest zeroed (static shape)."""
    x = X(ins, "X")
    i = X(ins, "I").reshape(()).astype(jnp.int32)
    table = X(ins, "RankTable")
    alive = jnp.sum((table[:, 1] > i)).astype(jnp.int32)
    mask = (jnp.arange(x.shape[0]) < alive).astype(x.dtype)
    return {"Out": [x * mask.reshape((-1,) + (1,) * (x.ndim - 1))]}


@register_op("rnn_memory_helper")
def _rnn_memory_helper(ctx, ins, attrs):
    return {"Out": [X(ins, "X")]}


@register_op("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs):
    """ref split_lod_tensor_op.cc (IfElse input router).  Dense-masked:
    both outputs keep the full batch with non-selected rows zeroed; the
    mask travels with them (static shapes — the reference physically
    splits, which is a dynamic shape)."""
    x, mask = X(ins, "X"), X(ins, "Mask")
    m = mask.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    return {"OutTrue": [x * m], "OutFalse": [x * (1 - m)]}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs):
    """ref merge_lod_tensor_op.cc: row-wise select by mask."""
    mask = X(ins, "Mask")
    t, f = X(ins, "InTrue"), X(ins, "InFalse")
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1)).astype(bool)
    return {"Out": [jnp.where(m, t, f)]}


alias_op("merge_lod_tensor_infer", "merge_lod_tensor")
alias_op("lod_tensor_to_array", "lod_reset")    # dense: values unchanged
alias_op("array_to_lod_tensor", "lod_reset")


# -- sequence stragglers -----------------------------------------------------

@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """ref operators/sequence_conv_op.cc: sliding window of
    ``context_length`` rows, linearly projected.  Dense [b, t, d] input."""
    x, w = X(ins, "X"), X(ins, "Filter")
    # both attr spellings exist in the reference (op proto snake_case,
    # Python layer camelCase)
    clen = attrs.get("contextLength", attrs.get("context_length", 3))
    cstart = attrs.get("contextStart",
                       attrs.get("context_start", -(clen // 2)))
    b, t, d = x.shape
    cols = []
    for o in range(clen):
        shift = cstart + o
        cols.append(jnp.roll(x, -shift, axis=1) *
                    ((jnp.arange(t) + shift >= 0) &
                     (jnp.arange(t) + shift < t)).astype(x.dtype)[None, :,
                                                                  None])
    ctx_mat = jnp.concatenate(cols, axis=-1)          # [b, t, clen*d]
    return {"Out": [ctx_mat @ w]}


@register_op("sequence_scatter")
def _sequence_scatter(ctx, ins, attrs):
    """ref sequence_scatter_op.cc: per-sequence scatter-add of updates at
    ids (dense: ids/updates [b, k], X [b, d])."""
    x, ids, upd = X(ins, "X"), X(ins, "Ids"), X(ins, "Updates")
    b = x.shape[0]
    return {"Out": [x.at[jnp.arange(b)[:, None], ids].add(upd)]}


@register_op("sequence_topk_avg_pooling")
def _sequence_topk_avg_pooling(ctx, ins, attrs):
    """ref sequence_topk_avg_pooling_op.cc: per row+channel, average of the
    top-k values (dense [b, c, t] input), one output column per k."""
    x = X(ins, "X")
    topks = attrs.get("topks", [1])
    sorted_x = jnp.sort(x, axis=-1)[..., ::-1]
    outs = []
    for k in topks:
        outs.append(jnp.mean(sorted_x[..., :k], axis=-1))
    return {"Out": [jnp.stack(outs, axis=-1).reshape(x.shape[0], -1)],
            "pos": [jnp.argsort(-x, axis=-1)[..., :max(topks)]
                    .astype(jnp.int32)]}


@register_op("match_matrix_tensor")
def _match_matrix_tensor(ctx, ins, attrs):
    """ref match_matrix_tensor_op.cc: bilinear match x·W·yᵀ per channel.
    Dense x [b, tx, d], y [b, ty, d], W [d, c, d] → [b, c, tx, ty]."""
    x, y, w = X(ins, "X"), X(ins, "Y"), X(ins, "W")
    out = jnp.einsum("bxd,dce,bye->bcxy", x, w, y)
    return {"Out": [out], "Tmp": [jnp.einsum("bxd,dce->bcxe", x, w)]}


@register_op("var_conv_2d")
def _var_conv_2d(ctx, ins, attrs):
    """ref var_conv_2d_op.cc: conv over per-sequence 2-D feature maps;
    dense equivalent is a grouped conv2d on [b, c, h, w]."""
    from .nn_ops import _conv2d
    return {"Out": _conv2d(ctx, {"Input": ins.get("X"),
                                 "Filter": ins.get("W")}, attrs)["Output"]}


@register_op("filter_by_instag")
def _filter_by_instag(ctx, ins, attrs):
    """ref filter_by_instag_op.cc: keep rows whose tag set intersects the
    filter tags.  Dense-masked: rows stay, non-matching ones are zeroed and
    LossWeight marks survivors (the reference compacts rows — dynamic
    shape)."""
    x = X(ins, "Ins")
    tags = X(ins, "Ins_tag")           # [b] one tag per row (dense form)
    filt = X(ins, "Filter_tag")        # [k]
    keep = jnp.isin(tags.reshape(-1), filt.reshape(-1))
    w = keep.astype(jnp.float32)
    return {"Out": [x * w.reshape((-1,) + (1,) * (x.ndim - 1))],
            "LossWeight": [w.reshape(-1, 1)],
            "IndexMap": [jnp.stack([jnp.arange(x.shape[0]),
                                    jnp.arange(x.shape[0])],
                                   axis=1).astype(ids_dtype())]}


# -- PS id sharding (dense-masked; the native PS plane routes rows itself) ---

@register_op("split_ids", no_grad=True, raw=True)
def _split_ids(ctx, block, op, state):
    """ref split_ids_op.cc: shard ids round-robin by id % n (n = number of
    Out vars, as in the reference).  Dense form: every shard output keeps
    the input shape with foreign ids as -1."""
    ids = state.read(block, op.input("Ids")[0])
    out_names = op.output("Out")
    n = max(len(out_names), 1)
    for i, name in enumerate(out_names):
        state.write(name, jnp.where(ids % n == i, ids, -1))


@register_op("merge_ids", no_grad=True)
def _merge_ids(ctx, ins, attrs):
    """ref merge_ids_op.cc: row lookups return to original positions.
    Dense form: shard rows carry zeros for foreign ids, so merge = sum."""
    rows = XS(ins, "X")
    out = rows[0]
    for r in rows[1:]:
        out = out + r
    return {"Out": [out]}


@register_op("split_selected_rows", no_grad=True)
def _split_selected_rows(ctx, ins, attrs):
    """ref split_selected_rows_op.cc: slice rows into height sections."""
    x = X(ins, "X")
    sections = attrs.get("height_sections", [x.shape[0]])
    outs, start = [], 0
    for s in sections:
        outs.append(jax.lax.slice_in_dim(x, start, start + s, axis=0))
        start += s
    return {"Out": outs}


@register_op("coalesce_tensor", no_grad=True)
def _coalesce_tensor(ctx, ins, attrs):
    """ref coalesce_tensor_op.cc: pack tensors into one contiguous buffer
    (fused-allreduce staging).  XLA owns real buffer placement; the fused
    view is the concat of flattened inputs, and the per-tensor outputs
    pass through."""
    xs = XS(ins, "Input")
    fused = jnp.concatenate([a.reshape(-1) for a in xs])
    return {"FusedOutput": [fused], "Output": list(xs)}


# -- dygraph collectives (ref operators/distributed_ops/allreduce_op.cc) -----

@register_op("allreduce")
def _allreduce(ctx, ins, attrs):
    from ..distributed.collective_ops import _axis
    from jax import lax
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    return {"Out": [lax.psum(x, ax) if ax is not None else x]}


@register_op("broadcast")
def _broadcast(ctx, ins, attrs):
    from ..distributed.collective_ops import _axis
    from jax import lax
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    root = int(attrs.get("root", 0) or 0)
    return {"Out": [lax.all_gather(x, ax)[root]]}


@register_op("sync_batch_norm")
def _sync_batch_norm(ctx, ins, attrs):
    """ref operators/sync_batch_norm_op.cu: BN statistics reduced across
    the data-parallel group (psum over the mesh axis) so every replica
    normalizes with GLOBAL batch moments."""
    from ..distributed.collective_ops import _axis
    from jax import lax
    from .nn_ops import _bn_axes
    x = X(ins, "X")
    scale, bias = X(ins, "Scale"), X(ins, "Bias")
    mean, var = X(ins, "Mean"), X(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    is_test = attrs.get("is_test", False) or attrs.get("use_global_stats",
                                                       False)
    axes, bshape = _bn_axes(layout, x.ndim)
    xf = x.astype(jnp.float32)
    if is_test:
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        ax = _axis(ctx, attrs)
        cnt = float(np.prod([x.shape[a] for a in axes]))
        s1 = jnp.sum(xf, axis=axes)
        s2 = jnp.sum(jnp.square(xf), axis=axes)
        if ax is not None:
            s1 = lax.psum(s1, ax)
            s2 = lax.psum(s2, ax)
            cnt = cnt * lax.psum(1, ax)
        m = s1 / cnt
        v = s2 / cnt - jnp.square(m)
        mean_out = mean * momentum + m * (1 - momentum)
        var_out = var * momentum + v * (1 - momentum)
    inv = jax.lax.rsqrt(v.reshape(bshape) + eps)
    y = (xf - m.reshape(bshape)) * inv * scale.reshape(bshape) + \
        bias.reshape(bshape)
    return {"Y": [y.astype(x.dtype)], "MeanOut": [mean_out],
            "VarianceOut": [var_out], "SavedMean": [m],
            "SavedVariance": [jax.lax.rsqrt(v + eps)]}


@register_op("dgc", no_grad=True)
def _dgc(ctx, ins, attrs):
    """ref operators/dgc_op.cc: the compression half of DGC (the sync half
    is dgc_allreduce).  u/v updates + top-k selection; EncodeGrad carries
    (idx, val) pairs as a dense [2k] vector."""
    u, v, g = X(ins, "U"), X(ins, "V"), X(ins, "Grad")
    m = attrs.get("m", 0.9)
    ratio = 1.0 - attrs.get("sparsity", [0.999])[-1] \
        if isinstance(attrs.get("sparsity"), (list, tuple)) \
        else 1.0 - attrs.get("sparsity", 0.999)
    gf = g.reshape(-1).astype(jnp.float32)
    k = max(1, int(round(gf.shape[0] * ratio)))
    u_new = m * u.reshape(-1) + gf
    v_new = v.reshape(-1) + u_new
    _, idx = jax.lax.top_k(jnp.abs(v_new), k)
    vals = v_new[idx]
    keep = jnp.ones_like(gf).at[idx].set(0.0)
    grad_out = jnp.zeros_like(gf).at[idx].set(vals)
    encode = jnp.concatenate([idx.astype(jnp.float32), vals])
    return {"U_out": [u_new * keep], "V_out": [v_new * keep],
            "EncodeGrad": [encode], "Grad_out": [grad_out.reshape(g.shape)],
            "GatherBuff": [encode]}


# -- fused / fusion op family (ref operators/fused/) -------------------------
# These exist in the reference as hand-fused CPU kernels; here they are
# COMPOSITIONS of the already-registered lowerings — XLA fuses the pieces,
# so the fused registration is an API/graph-compat surface, not a perf
# feature (fusion is the compiler's job on TPU).

def _call(op_type, ctx, ins, attrs):
    return registry.get_op_info(op_type).lower(ctx, ins, attrs)


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ctx, ins, attrs):
    """ref fused/fusion_squared_mat_sub_op.cc:
    out = scalar · ((XY)² − X²Y²)."""
    x, y = X(ins, "X"), X(ins, "Y")
    xy = x @ y
    x2y2 = jnp.square(x) @ jnp.square(y)
    out = attrs.get("scalar", 1.0) * (jnp.square(xy) - x2y2)
    return {"SquaredXY": [jnp.square(xy)], "SquaredX": [jnp.square(x)],
            "SquaredY": [jnp.square(y)], "Out": [out]}


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ctx, ins, attrs):
    """ref fused/fusion_repeated_fc_relu_op.cc: chain of fc+relu."""
    x = X(ins, "X")
    ws = XS(ins, "W")
    bs = XS(ins, "Bias")
    outs = []
    for i, (w, b) in enumerate(zip(ws, bs)):
        x = x.reshape(x.shape[0], -1) @ w + b.reshape(1, -1)
        if i < len(ws) - 1:
            x = jax.nn.relu(x)
        outs.append(x)
    return {"ReluOut": outs[:-1], "Out": [jax.nn.relu(outs[-1])]}


@register_op("fused_fc_elementwise_layernorm")
def _fused_fc_elementwise_layernorm(ctx, ins, attrs):
    """ref fused/fused_fc_elementwise_layernorm_op.cc:
    layer_norm(fc(x) + y)."""
    x, w = X(ins, "X"), X(ins, "W")
    b = X(ins, "Bias0")
    y = X(ins, "Y")
    scale, bias1 = X(ins, "Scale"), X(ins, "Bias1")
    h = x.reshape(x.shape[0], -1) @ w
    if b is not None:
        h = h + b.reshape(1, -1)
    h = h + y
    eps = attrs.get("epsilon", 1e-5)
    m = jnp.mean(h, axis=-1, keepdims=True)
    v = jnp.var(h, axis=-1, keepdims=True)
    out = (h - m) * jax.lax.rsqrt(v + eps)
    if scale is not None:
        out = out * scale.reshape(1, -1)
    if bias1 is not None:
        out = out + bias1.reshape(1, -1)
    return {"Out": [out], "Mean": [m.reshape(-1)],
            "Variance": [v.reshape(-1)]}


@register_op("fused_embedding_seq_pool")
def _fused_embedding_seq_pool(ctx, ins, attrs):
    """ref fused/fused_embedding_seq_pool_op.cc: lookup + sum-pool over the
    time dim (dense ids [b, t])."""
    w, ids = X(ins, "W"), X(ins, "Ids")
    emb = w[ids.reshape(ids.shape[0], -1)]
    return {"Out": [jnp.sum(emb, axis=1)]}


@register_op("fusion_seqpool_concat")
def _fusion_seqpool_concat(ctx, ins, attrs):
    """ref fused/fusion_seqpool_concat_op.cc: pool each [b,t,d] input over
    t, concat on features."""
    xs = XS(ins, "X")
    ptype = attrs.get("pooltype", "SUM").upper()
    red = {"SUM": jnp.sum, "AVERAGE": jnp.mean, "SQRT": jnp.sum,
           "MAX": jnp.max, "LAST": None, "FIRST": None}[ptype]
    pooled = []
    for x in xs:
        if ptype == "LAST":
            pooled.append(x[:, -1])
        elif ptype == "FIRST":
            pooled.append(x[:, 0])
        else:
            p = red(x, axis=1)
            if ptype == "SQRT":
                p = p / jnp.sqrt(float(x.shape[1]))
            pooled.append(p)
    return {"Out": [jnp.concatenate(pooled, axis=-1)]}


@register_op("fusion_seqpool_cvm_concat")
def _fusion_seqpool_cvm_concat(ctx, ins, attrs):
    """ref fused/fusion_seqpool_cvm_concat_op.cc: seqpool → cvm → concat."""
    pooled = _call("fusion_seqpool_concat", ctx, ins, attrs)["Out"][0]
    return {"Out": [_cvm(ctx, {"X": [pooled]}, attrs)["Y"][0]]}


@register_op("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ctx, ins, attrs):
    """ref fused/fusion_transpose_flatten_concat_op.cc."""
    xs = XS(ins, "X")
    perm = attrs.get("trans_axis", [0, 2, 3, 1])
    axis = attrs.get("concat_axis", 1)
    flat = [jnp.transpose(x, perm).reshape(x.shape[0], -1) for x in xs]
    return {"Out": [jnp.concatenate(flat, axis=axis if axis < 2 else 1)]}


@register_op("fusion_gru")
def _fusion_gru(ctx, ins, attrs):
    """ref fused/fusion_gru_op.cc: x·Wx projection fused in front of the
    standard GRU recurrence; delegates to the gru lowering."""
    x = X(ins, "X")
    wx, wh = X(ins, "WeightX"), X(ins, "WeightH")
    proj = x @ wx                          # [b, t, 3d]
    ins2 = {"Input": [proj], "Weight": [wh], "Bias": ins.get("Bias"),
            "H0": ins.get("H0"), "SeqLen": ins.get("SeqLen")}
    out = _call("gru", ctx, ins2, attrs)
    return {"Hidden": out["Hidden"], "XX": [proj],
            "BatchedInput": [proj], "BatchedOut": out["Hidden"]}


@register_op("fusion_lstm")
def _fusion_lstm(ctx, ins, attrs):
    """ref fused/fusion_lstm_op.cc: fused x·Wx + LSTM recurrence."""
    x = X(ins, "X")
    wx, wh = X(ins, "WeightX"), X(ins, "WeightH")
    proj = x @ wx                          # [b, t, 4d]
    ins2 = {"Input": [proj], "Weight": [wh], "Bias": ins.get("Bias"),
            "H0": ins.get("H0"), "C0": ins.get("C0"),
            "SeqLen": ins.get("SeqLen")}
    out = _call("lstm", ctx, ins2, attrs)
    return {"Hidden": out["Hidden"], "Cell": out["Cell"], "XX": [proj]}


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """ref fused/fused_embedding_fc_lstm_op.cc: ids → embedding rows used
    directly as the 4d gate projection, then LSTM."""
    ids = X(ins, "Ids")
    emb = X(ins, "Embeddings")             # [V, 4d] pre-multiplied table
    proj = emb[ids.reshape(ids.shape[0], -1)]
    ins2 = {"Input": [proj], "Weight": ins.get("WeightH"),
            "Bias": ins.get("Bias"), "H0": ins.get("H0"),
            "C0": ins.get("C0"), "SeqLen": ins.get("SeqLen")}
    out = _call("lstm", ctx, ins2, attrs)
    return {"Hidden": out["Hidden"], "Cell": out["Cell"], "XX": [proj]}


@register_op("attention_lstm")
def _attention_lstm(ctx, ins, attrs):
    """ref fused/attention_lstm_op.cc: per step, softmax attention over the
    encoder states conditioned on the previous cell, then one LSTM step."""
    x = X(ins, "X")                        # [b, t, d]
    c0 = X(ins, "C0")
    h0 = X(ins, "H0")
    att_w = X(ins, "AttentionWeight")      # [d + d, 1]
    lstm_w = X(ins, "LSTMWeight")          # [d + d, 4d]
    lstm_b = X(ins, "LSTMBias")            # [1, 4d]
    b, t, d = x.shape
    dh = lstm_w.shape[1] // 4
    if h0 is None:
        h0 = jnp.zeros((b, dh), x.dtype)

    def step(carry, _):
        h, c = carry
        # attention scores from [x_t ; c] per time step
        cexp = jnp.broadcast_to(c[:, None, :], (b, t, c.shape[-1]))
        feat = jnp.concatenate([x, cexp], axis=-1)
        scores = jax.nn.softmax(
            (feat @ att_w).squeeze(-1), axis=-1)       # [b, t]
        ctx_vec = jnp.einsum("bt,btd->bd", scores, x)
        gates = jnp.concatenate([ctx_vec, h], axis=-1) @ lstm_w + \
            lstm_b.reshape(-1)
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(gf) * c + \
            jax.nn.sigmoid(gi) * jnp.tanh(gc)
        h_new = jax.nn.sigmoid(go) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (h_f, c_f), hs = jax.lax.scan(step, (h0, c0), None, length=t)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "Cell": [c_f],
            "AttentionedX": [x], "AttentionFCOut": [h_f],
            "LSTMX": [x], "LSTMOUT": [h_f]}


@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """ref fused/fusion_seqconv_eltadd_relu_op.cc:
    relu(sequence_conv(x) + b)."""
    conv = _call("sequence_conv", ctx,
                 {"X": ins.get("X"), "Filter": ins.get("Filter")},
                 {"context_length": attrs.get("contextLength", 3),
                  "context_start": attrs.get("contextStart", 0)})["Out"][0]
    b = X(ins, "Bias")
    return {"Out": [jax.nn.relu(conv + b.reshape(1, 1, -1))],
            "ColMat": [conv]}


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """ref fused/fusion_seqexpand_concat_fc_op.cc: broadcast the second
    (per-sequence) inputs over time, concat features, one fc + act."""
    xs = XS(ins, "X")
    w = X(ins, "FCWeight")
    bias = X(ins, "FCBias")
    base = xs[0]                           # [b, t, d0]
    b_, t = base.shape[0], base.shape[1]
    feats = [base]
    for extra in xs[1:]:                   # [b, d] broadcast over t
        feats.append(jnp.broadcast_to(extra[:, None, :],
                                      (b_, t, extra.shape[-1])))
    cat = jnp.concatenate(feats, axis=-1)
    out = cat @ w
    if bias is not None:
        out = out + bias.reshape(1, 1, -1)
    act = attrs.get("fc_activation", "identity")
    if act not in ("identity", ""):
        from .math_ops import _ACTIVATIONS
        out = _ACTIVATIONS[act](out)
    return {"Out": [out], "FCOut": [out]}


# -- conv stragglers ---------------------------------------------------------

@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """ref operators/conv_transpose_op.cc (3-D)."""
    from .nn_ops import _conv_transpose_nd
    x, w = X(ins, "Input"), X(ins, "Filter")
    out = _conv_transpose_nd(
        x, w, list(attrs.get("strides", [1, 1, 1])),
        list(attrs.get("paddings", [0, 0, 0])),
        list(attrs.get("dilations", [1, 1, 1])),
        attrs.get("groups", 1) or 1, 3)
    return {"Output": [out]}


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, ins, attrs):
    a = dict(attrs)
    a["groups"] = X(ins, "Input").shape[1]
    return _call("conv2d_transpose", ctx, ins, a)


@register_op("conv2d_fusion")
def _conv2d_fusion(ctx, ins, attrs):
    """ref fused/conv2d_fusion_op.cc: conv + bias + (residual) + act."""
    out = _call("conv2d", ctx, ins, attrs)["Output"][0]
    b = X(ins, "Bias")
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    res = X(ins, "ResidualData")
    if res is not None:
        out = out + res
    act = attrs.get("activation", "relu")
    if act and act != "identity":
        from .math_ops import _ACTIVATIONS
        out = _ACTIVATIONS[act](out)
    return {"Output": [out]}


@register_op("spectral_norm")
def _spectral_norm(ctx, ins, attrs):
    """ref operators/spectral_norm_op.cc: weight / σ_max via power
    iteration on the stored u/v vectors."""
    w, u, v = X(ins, "Weight"), X(ins, "U"), X(ins, "V")
    dim = attrs.get("dim", 0)
    iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
    for _ in range(max(iters, 0)):
        v = mat.T @ u.reshape(-1)
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = u.reshape(-1)
    v = v.reshape(-1)
    sigma = u @ mat @ v
    return {"Out": [w / sigma]}


@register_op("detection_map", no_grad=True)
def _detection_map(ctx, ins, attrs):
    """ref operators/detection_map_op.cc — host-side mAP via the metrics
    implementation (pure_callback; metric ops are not on the training hot
    path)."""
    det = X(ins, "DetectRes")      # [n, 6] label,score,x1,y1,x2,y2
    label = X(ins, "Label")        # [m, 5] or [m, 6]
    overlap = attrs.get("overlap_threshold", 0.5)
    ap_version = attrs.get("ap_type", attrs.get("ap_version", "integral"))

    evaluate_difficult = attrs.get("evaluate_difficult", True)

    def host(det_v, label_v):
        from ..metrics import DetectionMAP
        m = DetectionMAP(overlap_threshold=overlap,
                         evaluate_difficult=evaluate_difficult,
                         ap_version=ap_version)
        lab = np.asarray(label_v, np.float64)
        if lab.shape[-1] == 6:     # [label, difficult, x1, y1, x2, y2] →
            # metrics order [label, x1, y1, x2, y2, difficult]
            lab = lab[:, [0, 2, 3, 4, 5, 1]]
        m.update(np.asarray(det_v, np.float64), lab)
        try:
            return np.float32(m.eval())
        except ValueError:
            return np.float32(0.0)

    out = jax.pure_callback(
        host, jax.ShapeDtypeStruct((), jnp.float32), det, label)
    return {"MAP": [out.reshape(1)],
            "AccumPosCount": [jnp.zeros((1,), jnp.int32)],
            "AccumTruePos": [jnp.zeros((1, 2), jnp.float32)],
            "AccumFalsePos": [jnp.zeros((1, 2), jnp.float32)]}


# -- final stragglers --------------------------------------------------------

@register_op("affine_grid")
def _affine_grid(ctx, ins, attrs):
    """ref operators/affine_grid_op.cc: theta [N,2,3] → normalized sampling
    grid [N,H,W,2] over [-1,1]² (pairs with grid_sampler)."""
    theta = X(ins, "Theta")
    shape = attrs.get("output_shape") or None
    if not shape:
        hw = X(ins, "OutputShape")
        if isinstance(hw, jax.core.Tracer):
            raise TypeError(
                "affine_grid OutputShape must be a compile-time constant "
                "under XLA; pass the output_shape attr instead")
        shape = [int(v) for v in np.asarray(hw)]
    n, _, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)                  # [h, w]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)      # [h, w, 3]
    grid = jnp.einsum("hwk,nck->nhwc", base, theta)
    return {"Output": [grid]}


@register_op("lstmp")
def _lstmp(ctx, ins, attrs):
    """ref operators/lstmp_op.cc: LSTM with a recurrent projection layer
    (h = (o ⊙ tanh(c)) · P), the LARK/ASR recipe."""
    x = X(ins, "Input")                # [b, t, 4d] pre-projected
    w = X(ins, "Weight")               # [p, 4d] recurrent on the PROJECTION
    proj_w = X(ins, "ProjWeight")      # [d, p]
    bias = X(ins, "Bias")
    h0, c0 = X(ins, "H0"), X(ins, "C0")
    gate_act = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh}[
        attrs.get("gate_activation", "sigmoid")]
    act = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh}[
        attrs.get("cell_activation", "tanh")]
    proj_act = attrs.get("proj_activation", "tanh")
    use_peepholes = attrs.get("use_peepholes", False)
    b, t, d4 = x.shape
    d = d4 // 4
    p = proj_w.shape[1]
    w_ic = w_fc = w_oc = None
    if bias is not None:
        flat_b = bias.reshape(-1)
        x = x + flat_b[:4 * d]
        if use_peepholes:
            peep = flat_b[4 * d:]
            w_ic, w_fc, w_oc = peep[:d], peep[d:2 * d], peep[2 * d:3 * d]
    if h0 is None:
        h0 = jnp.zeros((b, p), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, d), x.dtype)

    def step(carry, xt):
        h, c = carry
        gates = xt + h @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        c_new = gate_act(gf) * c + gate_act(gi) * act(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        raw_h = gate_act(go) * act(c_new)
        h_new = raw_h @ proj_w
        if proj_act == "tanh":
            h_new = jnp.tanh(h_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(
        step, (h0, c0), jnp.swapaxes(x, 0, 1),
        reverse=attrs.get("is_reverse", False))
    return {"Projection": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "BatchGate": [x], "BatchCellPreAct": [cs[-1]],
            "BatchHidden": [hs[-1]]}


@register_op("cudnn_lstm")
def _cudnn_lstm(ctx, ins, attrs):
    """ref operators/cudnn_lstm_op.cu (single-layer unidirectional subset):
    flat weight blob unpacked to Wx/Wh/biases per the cudnn layout."""
    x = X(ins, "Input")                # [t, b, in] time-major (cudnn)
    w = X(ins, "W").reshape(-1)
    init_h, init_c = X(ins, "InitH"), X(ins, "InitC")
    hidden = int(attrs.get("hidden_size"))
    if attrs.get("num_layers", 1) != 1 or attrs.get("is_bidirec", False):
        raise NotImplementedError(
            "cudnn_lstm lowering covers num_layers=1 unidirectional; stack "
            "the lstm op for deeper/bidirectional nets")
    t, b, d_in = x.shape
    o = 0
    wx = w[o:o + 4 * hidden * d_in].reshape(4, hidden, d_in); o += 4 * hidden * d_in
    wh = w[o:o + 4 * hidden * hidden].reshape(4, hidden, hidden); o += 4 * hidden * hidden
    bx = w[o:o + 4 * hidden].reshape(4, hidden); o += 4 * hidden
    bh = w[o:o + 4 * hidden].reshape(4, hidden)
    # cudnn gate order i,f,c,o matches the lstm op's
    wx2 = jnp.concatenate([wx[g].T for g in range(4)], axis=1)  # [d_in, 4h]
    wh2 = jnp.concatenate([wh[g].T for g in range(4)], axis=1)  # [h, 4h]
    bias = (bx + bh).reshape(1, -1)
    xb = jnp.swapaxes(x, 0, 1)          # [b, t, d_in]
    proj = xb @ wx2
    h0 = init_h.reshape(b, hidden) if init_h is not None else None
    c0 = init_c.reshape(b, hidden) if init_c is not None else None
    out = _call("lstm", ctx,
                {"Input": [proj], "Weight": [wh2], "Bias": [bias],
                 "H0": [h0] if h0 is not None else [],
                 "C0": [c0] if c0 is not None else []},
                {"gate_activation": "sigmoid", "cell_activation": "tanh",
                 "candidate_activation": "tanh"})
    hs = jnp.swapaxes(out["Hidden"][0], 0, 1)       # back to [t, b, h]
    return {"Out": [hs], "last_h": [out["LastH"][0][None]],
            "last_c": [out["LastC"][0][None]],
            "Reserve": [jnp.zeros((1,), jnp.float32)],
            "StateOut": [jnp.zeros((1,), jnp.float32)]}


@register_op("recurrent", no_grad=True, raw=True)
def _recurrent(ctx, block, op, state):
    """ref operators/recurrent_op.cc: run the step block once per time
    step, threading `states` → `ex_states`, stacking `outputs`.  Sequence
    inputs are time-major (sliced on dim 0), exactly the reference's step
    slicing; the whole loop compiles to one lax.scan."""
    from .control_flow_ops import _trace_subblock
    sub = op.attrs["sub_block"]
    states = op.attrs.get("states", [])
    ex_states = op.attrs.get("ex_states", [])
    seq_names = op.input("inputs")
    init_names = op.input("initial_states")
    param_names = op.input("parameters")
    out_names = op.output("outputs")
    consts = {n: state.read(block, n) for n in param_names}
    xs = tuple(state.read(block, n) for n in seq_names)
    carry0 = tuple(state.read(block, n) for n in init_names)

    def step(carry, xt):
        env = dict(consts)
        env.update(zip(ex_states, carry))
        env.update(zip(seq_names, xt))
        env = _trace_subblock(ctx, sub, env)
        return (tuple(env[n] for n in states),
                tuple(env[n] for n in out_names))

    _, outs = jax.lax.scan(step, carry0, xs,
                           reverse=op.attrs.get("reverse", False))
    for n, v in zip(out_names, outs):
        state.write(n, v)


def _bilinear_sample(feat, py, px):
    """feat [C, H, W]; py/px arbitrary-shape float coords → [C, *coords]."""
    c, h, w = feat.shape
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy = py - y0
    wx = px - x0
    out = 0.0
    for dy, sy in ((0, 1 - wy), (1, wy)):
        for dx, sx in ((0, 1 - wx), (1, wx)):
            yy = y0 + dy
            xx = x0 + dx
            inside = ((yy >= 0) & (yy <= h - 1) &
                      (xx >= 0) & (xx <= w - 1))
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            out = out + feat[:, yi, xi] * (sy * sx * inside)[None]
    return out


@register_op("deformable_conv")
def _deformable_conv(ctx, ins, attrs):
    """ref operators/deformable_conv_op.cc (v2): each kernel tap samples at
    a learned offset, optionally modulated by Mask; realized as bilinear
    gathers + one matmul (the deformable im2col, MXU-shaped)."""
    x = X(ins, "Input")              # [N, C, H, W]
    offset = X(ins, "Offset")        # [N, 2*kh*kw, Ho, Wo] (y, x pairs)
    mask = X(ins, "Mask")            # [N, kh*kw, Ho, Wo] or None (v1)
    w = X(ins, "Filter")             # [Co, C/g, kh, kw]
    sh, sw = attrs.get("strides", [1, 1])
    ph, pw = attrs.get("paddings", [0, 0])
    dh, dw = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1) or 1
    n, c, h, wd = x.shape
    co, cpg, kh, kw = w.shape
    ho = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    wo = (wd + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    base_y = (jnp.arange(ho) * sh - ph)[:, None]          # [Ho, 1]
    base_x = (jnp.arange(wo) * sw - pw)[None, :]          # [1, Wo]

    def one_image(xi, offi, mi):
        cols = []
        for t in range(kh * kw):
            ky, kx = t // kw, t % kw
            py = base_y + ky * dh + offi[2 * t]           # [Ho, Wo]
            px = base_x + kx * dw + offi[2 * t + 1]
            s = _bilinear_sample(xi, py, px)              # [C, Ho, Wo]
            if mi is not None:
                s = s * mi[t][None]
            cols.append(s)
        return jnp.stack(cols, axis=1)                    # [C, kh*kw, Ho, Wo]

    if mask is not None:
        cols = jax.vmap(one_image)(x, offset, mask)
    else:
        cols = jax.vmap(lambda xi, offi: one_image(xi, offi, None))(
            x, offset)
    # cols: [N, C, kh*kw, Ho, Wo] → grouped matmul with the filter
    cols_g = cols.reshape(n, groups, cpg * kh * kw, ho * wo)
    w_g = w.reshape(groups, co // groups, cpg * kh * kw)
    out = jnp.einsum("ngkp,gok->ngop", cols_g, w_g)
    return {"Output": [out.reshape(n, co, ho, wo)]}


@register_op("deformable_conv_v1")
def _deformable_conv_v1(ctx, ins, attrs):
    ins2 = dict(ins)
    ins2["Mask"] = []
    return {"Output": _deformable_conv(ctx, ins2, attrs)["Output"]}


@register_op("deformable_psroi_pooling")
def _deformable_psroi_pooling(ctx, ins, attrs):
    """ref operators/deformable_psroi_pooling_op.cc: position-sensitive ROI
    pooling with per-part learned offsets (deformable R-FCN head)."""
    x = X(ins, "Input")              # [N, C, H, W], C = out_c * ph * pw
    rois = X(ins, "ROIs")            # [R, 4] x1,y1,x2,y2
    trans = X(ins, "Trans")          # [R, 2, ph, pw] offsets or None
    spatial_scale = attrs.get("spatial_scale", 1.0)
    out_dim = attrs.get("output_dim")
    group = attrs.get("group_size", [1, 1])[0]
    pooled = attrs.get("pooled_height", attrs.get("pooled_size", 7))
    part = attrs.get("part_size", [pooled, pooled])[0]
    tstd = attrs.get("trans_std", 0.1)
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph_ = pooled
    from .detection_ops import _rois_batch_index
    roi_imgs = _rois_batch_index(X(ins, "RoisNum"), r, n)

    def one_roi(roi, tr, bi):
        img = x[bi]
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        bin_w, bin_h = rw / ph_, rh / ph_
        outs = []
        for i in range(ph_):
            for j in range(ph_):
                off_y = tr[0, min(i * part // ph_, part - 1),
                           min(j * part // ph_, part - 1)] * tstd * rh \
                    if tr is not None else 0.0
                off_x = tr[1, min(i * part // ph_, part - 1),
                           min(j * part // ph_, part - 1)] * tstd * rw \
                    if tr is not None else 0.0
                cy = y1 + (i + 0.5) * bin_h + off_y
                cx = x1 + (j + 0.5) * bin_w + off_x
                gi = min(i * group // ph_, group - 1)
                gj = min(j * group // ph_, group - 1)
                # output-channel-major layout, matching _psroi_pool
                # (detection_ops.py) and the reference kernel: channel for
                # output ctop at part (gi, gj) is ctop·group² + gi·group+gj
                feat = img[gi * group + gj::group * group][:out_dim]
                outs.append(_bilinear_sample(feat, cy[None, None],
                                             cx[None, None])[:, 0, 0])
        return jnp.stack(outs, -1).reshape(out_dim, ph_, ph_)

    if trans is not None:
        outs = jax.vmap(one_roi)(rois, trans, roi_imgs)
    else:
        outs = jax.vmap(lambda roi, bi: one_roi(roi, None, bi))(
            rois, roi_imgs)
    return {"Output": [outs], "TopCount": [jnp.ones_like(outs)]}


@register_op("conv2d_inception_fusion")
def _conv2d_inception_fusion(ctx, ins, attrs):
    """ref fused/fusion_conv_inception_op.cu: 4-branch inception cell —
    (avgpool→1×1), (1×1 direct channels), (grouped double-3×3 chain) —
    concatenated along channels with per-branch bias+relu."""
    x = X(ins, "Input")
    f = XS(ins, "Filter")
    bs = XS(ins, "Bias")

    def conv(inp, w, b, groups=1, k3=False):
        pad = 1 if k3 else 0
        out = jax.lax.conv_general_dilated(
            inp, w, window_strides=(1, 1),
            padding=[(pad, pad), (pad, pad)],
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return jax.nn.relu(out)

    # branch 0: 3x3 avg pool (same) → 1x1 conv
    pooled = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 1, 3, 3), (1, 1, 1, 1),
        [(0, 0), (0, 0), (1, 1), (1, 1)]) / 9.0
    b0 = conv(pooled, f[0], bs[0] if bs else None)
    # branch 1+2 stem: 1x1 conv; first oc1 channels pass through, the rest
    # feed the grouped double-3x3 chain
    u = conv(x, f[1], bs[1] if len(bs) > 1 else None)
    f2_in = f[2].shape[1] * 2                 # grouped (2) conv input
    oc1 = f[1].shape[0] - f2_in
    b1 = u[:, :oc1]
    v = u[:, oc1:]
    w2 = conv(v, f[2], bs[2] if len(bs) > 2 else None, groups=2, k3=True)
    f3_ic = f[3].shape[1]
    b2 = w2[:, :w2.shape[1] - f3_ic]
    b3 = conv(w2[:, w2.shape[1] - f3_ic:], f[3],
              bs[3] if len(bs) > 3 else None, k3=True)
    return {"Output": [jnp.concatenate([b0, b1, b2, b3], axis=1)],
            "TempOutput": [u]}
