"""Op lowering registry population — importing this package registers every
op's JAX lowering (the TPU stand-in for the reference's static
REGISTER_OPERATOR initializers)."""

from . import (attention_ops, control_flow_ops, detection_ops,  # noqa
               math_ops, metrics_ops, misc_ops, nn_ops, optimizer_ops,
               quant_ops, reduce_ops, rnn_ops, sequence_ops,
               structured_ops, tensor_ops)
from . import conv_bn_ops  # noqa
from . import fused_ops  # noqa  (analysis.fusion rewrite targets)
from . import moe_ops  # noqa
from . import compat_ops  # noqa  (must come last: aliases existing ops)
from ..framework.registry import registered_ops  # noqa
