"""Mixture-of-experts ops — the capability behind the mesh's ``ep`` axis.

No reference counterpart (the 2019 snapshot has no MoE); design follows
GShard/Switch-Transformer: top-1 gating, capacity-factor DENSE dispatch
(one-hot einsums — static shapes, XLA-friendly), per-expert FFN as one
batched matmul over the expert dimension.  Under a mesh with an ``ep``
axis the expert-major tensors are GSPMD-sharded on E (the layer annotates
the expert weights with dist_spec ``("ep", ...)``), which makes the
dispatch/combine einsums lower to all-to-alls over ICI — the standard
expert-parallel pattern.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import X


@register_op("switch_ffn")
def _switch_ffn(ctx, ins, attrs):
    """Switch-Transformer FFN: y = combine(expert_ffn(dispatch(x))).

    Inputs: X [B,T,d], GateW [d,E], W1 [E,d,f], B1 [E,f], W2 [E,f,d],
    B2 [E,d].  Outputs: Out [B,T,d], AuxLoss [] (load-balancing loss,
    E·Σ_e fraction_e·prob_e — add a small multiple to the training loss).
    Tokens beyond an expert's capacity are dropped (contribute zero),
    per the Switch recipe.
    """
    x, gw = X(ins, "X"), X(ins, "GateW")
    w1, b1 = X(ins, "W1"), X(ins, "B1")
    w2, b2 = X(ins, "W2"), X(ins, "B2")
    act = attrs.get("act", "relu")
    cf = float(attrs.get("capacity_factor", 1.25))
    B, T, d = x.shape
    E = gw.shape[-1]
    S = B * T
    cap = int(max(1, np.ceil(cf * S / E)))
    xt = x.reshape(S, d)

    # gating in f32 (tiny [S, E] tensors; router numerics matter)
    logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate = probs.max(axis=-1)
    idx = probs.argmax(axis=-1)

    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)            # [S, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1               # [S, E]
    dispatch = jax.nn.one_hot(pos, cap, dtype=x.dtype)          # [S, E, C]

    xe = jnp.einsum("sec,sd->ecd", dispatch, xt)                # [E, C, d]
    h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(x.dtype)) \
        + b1.astype(x.dtype)[:, None, :]
    h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
    ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(x.dtype)) \
        + b2.astype(x.dtype)[:, None, :]

    combine = dispatch * gate.astype(x.dtype)[:, None, None]    # [S, E, C]
    y = jnp.einsum("sec,ecd->sd", combine, ye)

    frac = onehot.astype(jnp.float32).mean(axis=0)              # tokens/e
    aux = (frac * probs.mean(axis=0)).sum() * E
    return {"Out": [y.reshape(B, T, d)], "AuxLoss": [aux]}
