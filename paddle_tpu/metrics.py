"""Stateful Python metric aggregators (ref ``python/paddle/fluid/metrics.py``).

These accumulate across minibatches host-side; the in-graph metric ops
(``accuracy``, ``auc`` — ``operators/metrics/``) produce the per-batch
statistics fed into ``update``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc",
           "DetectionMAP"]


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    """ref metrics.py MetricBase: name + reset/update/eval protocol."""

    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def get_config(self):
        states = {k: v for k, v in self.__dict__.items()
                  if not k.startswith("_")}
        return {"name": self._name, "states": states}

    def reset(self):
        for k in list(self.__dict__):
            if not k.startswith("_"):
                v = self.__dict__[k]
                self.__dict__[k] = 0.0 if np.isscalar(v) else \
                    type(v)() if isinstance(v, (list, dict)) else v * 0

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    """ref metrics.py CompositeMetric: fan one update into many metrics."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision = tp / (tp + fp) (ref metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        pos = preds == 1
        self.tp += float(np.sum(pos & (labels == 1)))
        self.fp += float(np.sum(pos & (labels != 1)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall = tp / (tp + fn) (ref metrics.py Recall)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).ravel()
        labels = _to_np(labels).astype(np.int64).ravel()
        true = labels == 1
        self.tp += float(np.sum(true & (preds == 1)))
        self.fn += float(np.sum(true & (preds != 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted running accuracy: feed the per-batch accuracy from the
    in-graph ``accuracy`` op plus the batch size (ref metrics.py Accuracy)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        value = float(np.asarray(value).ravel()[0])
        weight = float(weight)
        if weight < 0:
            raise ValueError("weight must be nonnegative")
        self.value += value * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy.eval before any update")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunking F1 from (num_infer, num_label, num_correct) counts produced
    by the ``chunk_eval`` op (ref metrics.py ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0.0
        self.num_label_chunks = 0.0
        self.num_correct_chunks = 0.0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += float(np.asarray(num_infer_chunks).ravel()[0])
        self.num_label_chunks += float(np.asarray(num_label_chunks).ravel()[0])
        self.num_correct_chunks += float(
            np.asarray(num_correct_chunks).ravel()[0])

    def eval(self):
        precision = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1


class EditDistance(MetricBase):
    """Average edit distance + instance error rate from the
    ``edit_distance`` op's (distances, seq_num) pair (ref metrics.py)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = _to_np(distances).astype(np.float64).ravel()
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(d > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance.eval before any update")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """ROC AUC via threshold-bucketed tp/fp histograms, trapezoid rule
    (ref metrics.py Auc — same bucket algorithm as the ``auc`` op)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        if curve not in ("ROC", "PR"):
            raise ValueError(f"curve must be ROC or PR, got {curve!r}")
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(num_thresholds + 1, np.int64)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).astype(np.int64).ravel()
        # preds: [N, 2] probability rows (ref expects softmax output)
        p1 = preds[:, -1] if preds.ndim == 2 else preds.ravel()
        idx = np.minimum((p1 * self._num_thresholds).astype(np.int64),
                         self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels != 1], 1)

    def eval(self):
        # cumulate from the highest threshold down: (tp, fp) at each cut
        tp = np.cumsum(self._stat_pos[::-1]).astype(np.float64)
        fp = np.cumsum(self._stat_neg[::-1]).astype(np.float64)
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        if self._curve == "ROC":
            tpr = np.concatenate([[0.0], tp / tot_pos])
            fpr = np.concatenate([[0.0], fp / tot_neg])
            return float(np.trapezoid(tpr, fpr))
        rec = np.concatenate([[0.0], tp / tot_pos])
        prec = np.concatenate([[1.0], tp / np.maximum(tp + fp, 1e-12)])
        return float(np.trapezoid(prec, rec))


class DetectionMAP(MetricBase):
    """Mean average precision for detection, 11-point interpolated or
    integral (ref metrics.py DetectionMAP / operators/detection_map_op).

    ``update(pred, gt)`` takes per-image lists:
      pred: [label, score, xmin, ymin, xmax, ymax] rows
      gt:   [label, xmin, ymin, xmax, ymax] or
            [label, xmin, ymin, xmax, ymax, difficult] rows
    With ``evaluate_difficult=False``, difficult gt boxes are excluded from
    the recall denominator and detections matching them count neither as
    true nor false positives (VOC convention, ref detection_map_op).
    """

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be integral|11point")
        self._iou = overlap_threshold
        self._evaluate_difficult = evaluate_difficult
        self._ap_version = ap_version
        self._preds = []      # (label, score, matched, ignored)
        self._gt_count = {}

    def reset(self):
        self._preds = []
        self._gt_count = {}

    @staticmethod
    def _iou_xyxy(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, pred, gt):
        pred = _to_np(pred).reshape(-1, 6)
        gt = _to_np(gt)
        gt = gt.reshape(-1, gt.shape[-1] if gt.ndim > 1 else 5)
        difficult = gt[:, 5].astype(bool) if gt.shape[1] > 5 else \
            np.zeros(len(gt), bool)
        count_mask = self._evaluate_difficult | ~difficult
        for lbl in set(gt[:, 0].astype(int)):
            self._gt_count[lbl] = self._gt_count.get(lbl, 0) + \
                int(np.sum((gt[:, 0].astype(int) == lbl) & count_mask))
        taken = set()
        for row in pred[np.argsort(-pred[:, 1])]:
            lbl, score = int(row[0]), float(row[1])
            best, best_j = 0.0, -1
            for j, g in enumerate(gt):
                if int(g[0]) != lbl or j in taken:
                    continue
                iou = self._iou_xyxy(row[2:], g[1:5])
                if iou > best:
                    best, best_j = iou, j
            matched = best >= self._iou and best_j >= 0
            ignored = matched and not count_mask[best_j]
            if matched:
                taken.add(best_j)
            self._preds.append((lbl, score, matched and not ignored,
                                ignored))

    def _ap(self, rec, prec):
        if self._ap_version == "11point":
            return float(np.mean([
                max([p for r, p in zip(rec, prec) if r >= t], default=0.0)
                for t in np.linspace(0, 1, 11)]))
        # VOC integral: interpolate precision with the running max over
        # LATER points (each recall gain is credited the best precision
        # still achievable at that recall or beyond)
        prec = np.maximum.accumulate(prec[::-1])[::-1]
        ap = 0.0
        prev_r = 0.0
        for r, p in zip(rec, prec):
            ap += (r - prev_r) * p
            prev_r = r
        return ap

    def eval(self):
        if not self._gt_count:
            raise ValueError("DetectionMAP.eval before any update")
        aps = []
        for lbl, n_gt in self._gt_count.items():
            rows = sorted((p for p in self._preds
                           if p[0] == lbl and not p[3]),
                          key=lambda t: -t[1])
            tp = np.cumsum([1 if m else 0 for _, _, m, _ in rows])
            fp = np.cumsum([0 if m else 1 for _, _, m, _ in rows])
            if len(rows) == 0:
                aps.append(0.0)
                continue
            rec = tp / max(n_gt, 1)
            prec = tp / np.maximum(tp + fp, 1e-12)
            aps.append(self._ap(rec, prec))
        return float(np.mean(aps))
