"""Trainer descriptors (ref ``python/paddle/fluid/trainer_desc.py:20,118,
139,158`` TrainerDesc/MultiTrainer/DistMultiTrainer/PipelineTrainer and
``framework/trainer_desc.proto``).

The reference serializes these to protobuf consumed by the C++ trainer
runtime; here the descriptor carries the same knobs as plain attributes.
``Executor.train_from_dataset(..., trainer_desc=...)`` consumes the
fetch/print configuration; thread_num/device_worker are accepted for API
parity (the XLA block-compiler owns intra-step parallelism, so there is no
thread-per-device loop to configure)."""

from __future__ import annotations

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer"]


class TrainerDesc:
    """ref trainer_desc.py:20 — thread count, fetch config, device worker."""

    def __init__(self):
        self._thread_num = 1
        self._device_worker = None
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100
        self._program = None
        self._infer = False
        self._dump_fields = []
        self._dump_fields_path = ""
        self._dump_converter = ""
        self.proto_desc = self          # parity: .proto_desc attr exists

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_device_worker(self, device_worker):
        self._device_worker = device_worker

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or [])
        self._print_period = int(print_period)

    def set_program(self, program):
        self._program = program

    def set_infer(self, infer):
        self._infer = bool(infer)

    # field-dump pipeline (ref trainer_desc.py:87-92 _set_dump_fields;
    # DistMultiTrainer dump workers, framework/trainer.h:92)
    def _set_dump_fields(self, dump_fields):
        self._dump_fields = [getattr(f, "name", f) for f in dump_fields]

    def _set_dump_fields_path(self, path):
        self._dump_fields_path = str(path)

    def _set_dump_converter(self, converter):
        self._dump_converter = str(converter)

    def _desc(self):
        return {
            "class": type(self).__name__,
            "thread_num": self._thread_num,
            "device_worker": type(self._device_worker).__name__
            if self._device_worker else None,
            "fetch_vars": [getattr(v, "name", v) for v in self._fetch_vars],
            "fetch_info": list(self._fetch_info),
            "print_period": self._print_period,
            "infer": self._infer,
        }


class MultiTrainer(TrainerDesc):
    """ref trainer_desc.py:118 — thread × HogwildWorker trainer."""


class DistMultiTrainer(TrainerDesc):
    """ref trainer_desc.py:139 — PS trainer with background dense pull."""


class PipelineTrainer(TrainerDesc):
    """ref trainer_desc.py:158 — section-pipeline trainer."""
