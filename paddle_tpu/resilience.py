"""Fault-tolerant training runtime: fault injection, retry/backoff,
preemption drain, and a hung-step watchdog.

The reference Fluid stack shipped a real failure story — ``FLAGS_rpc_retry_
times``/``FLAGS_rpc_deadline`` on the PS RPC plane (``grpc_client.cc``
retries) and ``checkpoint_notify`` snapshots — but until this layer the
rebuild only carried the flags.  On TPU the dominant failure mode is
preemption and transient infra flake, so every layer that talks to the
outside world (PS RPCs, the dataloader producer thread, XLA compiles,
checkpoint writes, executor dispatch) gets a supervision story here:

- **Fault injection** (``FLAGS_fault_inject="site:spec[;site:spec...]"``):
  deterministic, flag-driven fault hooks compiled into the dataloader
  producer, the compiler's graph-pass path, executor dispatch, checkpoint
  writes, and every ``PSClient`` RPC.  Spec grammar (comma-joined keys)::

      ps.put:every=3              # every 3rd call raises
      compile:once@2              # exactly the 2nd call (also once@step2)
      dataloader.produce:p=0.1,seed=7   # Bernoulli, deterministic stream
      checkpoint.write:times=2    # the first 2 calls
      executor.dispatch:once,hang=30    # 2nd form: hang instead of raise

  Injected faults raise :class:`InjectedFault` (transient by contract) and
  bump ``paddle_tpu_fault_injected_total{site=...}`` — so a test can assert
  the exact number of faults the spec implies.

- **Retry engine**: :func:`retry_call` runs a callable under a
  :class:`RetryPolicy` (exponential backoff + deterministic jitter, capped
  by an optional deadline).  Checkpoint writes, transient compile
  failures, and the PS injection plane ride it; PS *transport* retries
  belong to the native client (which already implements the
  ``FLAGS_rpc_retry_times`` loop and alone knows which ops are safe to
  replay) — the flags' side effects mirror
  ``FLAGS_rpc_retry_times``/``FLAGS_rpc_deadline`` into the env so
  ``set_flags`` finally governs that loop.  Every retry bumps
  ``paddle_tpu_retry_attempts_total{site=...}`` and records a
  ``retry.backoff`` tracer span; exhausted budgets bump
  ``paddle_tpu_retry_giveups_total{site=...}``.

- **Preemption drain** (:class:`PreemptionGuard`): a context manager that
  installs SIGTERM/SIGINT handlers; the training loop polls
  ``guard.preempted`` at step boundaries (the handler only sets a flag —
  never checkpoints mid-step), and guard exit drains the executor's
  in-flight throttle queue, writes an emergency ``CheckpointManager``
  checkpoint at the last *complete* step, exports telemetry, and exits
  cleanly.  :func:`resume_or_init` restarts a ``train_from_dataset``-style
  loop from the last complete step.

- **Hung-step watchdog** (``FLAGS_watchdog_timeout_s``): executor dispatch
  and fetch materialization run under ``WATCHDOG.watch(site)``; a step
  exceeding the deadline gets all thread stacks + the metrics registry +
  the telemetry ring dumped to ``FLAGS_watchdog_dump_dir`` and a
  :class:`HungStepError` naming the dump file raised in the hung thread —
  a diagnosable failure instead of a silent CI timeout.  (The async raise
  lands at the next Python bytecode boundary; a thread hung inside a C
  call still gets the dump immediately and the error on return.)

Every recovery action is observable through the PR 2 registry/tracer, so
the layer is testable end to end: inject faults, assert on exported
counters (``tools/resilience_smoke.py`` is the CI gate).
"""

from __future__ import annotations

import contextlib
import ctypes
import itertools
import json
import os
import random
import re
import signal
import sys
import tempfile
import threading
import time
import traceback
import zlib
from typing import Any, Callable, Dict, List, Optional

from . import monitor as _monitor

__all__ = [
    "InjectedFault", "HungStepError", "is_transient", "mark_transient",
    "FaultSpec", "parse_fault_inject", "configure", "maybe_inject",
    "backoff_schedule", "RetryPolicy", "retry_call",
    "PreemptionGuard", "resume_or_init",
    "Watchdog", "WATCHDOG", "dump_state",
]

# ---------------------------------------------------------------------------
# metrics (one family per recovery action; per-site label series)
# ---------------------------------------------------------------------------

_FAULT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_fault_injected_total",
    "faults fired by the FLAGS_fault_inject framework", ("site",))
_RETRY_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_retry_attempts_total",
    "retries performed after a transient failure (first attempts do not "
    "count — a clean run exports 0)", ("site",))
_GIVEUP_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_retry_giveups_total",
    "operations abandoned after exhausting their retry/deadline budget",
    ("site",))
_WATCHDOG_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_watchdog_fired_total",
    "hung-step watchdog expirations (each writes a stack+telemetry dump)",
    ("site",))
_PREEMPT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_preemption_signals_total",
    "SIGTERM/SIGINT deliveries observed by a PreemptionGuard", ("signal",))


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """A deterministic fault fired by ``FLAGS_fault_inject`` — transient by
    contract, so the retry engine absorbs it wherever a retry policy is
    installed (that asymmetry IS the test: sites with retries complete,
    sites without surface the fault)."""

    pt_transient = True

    def __init__(self, site: str, call_n: int, spec: str):
        super().__init__(
            f"injected fault at {site!r} (call #{call_n}, spec {spec!r})")
        self.site = site
        self.call_n = call_n


class HungStepError(RuntimeError):
    """Raised by the watchdog when a watched step exceeds
    ``FLAGS_watchdog_timeout_s``.  Never retryable: the hang already
    consumed the deadline, and the dump file is the diagnosis."""


def mark_transient(e: BaseException) -> BaseException:
    """Tag an exception as transient so :func:`is_transient` callers
    (compile retries, user-level ``retry_call`` policies) treat it as
    retryable."""
    e.pt_transient = True
    return e


def is_transient(e: BaseException) -> bool:
    return bool(getattr(e, "pt_transient", False))


# ---------------------------------------------------------------------------
# fault-injection framework
# ---------------------------------------------------------------------------

#: the sites the runtime has hooks at (documented contract; parsing warns
#: on unknown sites rather than failing — forward-compat with user hooks)
KNOWN_SITES = (
    "ps.put", "ps.get", "ps.push_dense", "ps.push_sparse", "ps.get_rows",
    "ps.put_typed", "ps.get_typed", "ps.push_typed",
    "dataloader.produce", "compile", "executor.dispatch",
    "fetch.materialize", "checkpoint.write",
)

_ONCE_RE = re.compile(r"^once(?:@(?:step)?(\d+))?$")


class FaultSpec:
    """One site's parsed injection spec + its thread-safe call counter."""

    def __init__(self, site: str, raw: str, every: int = 0, at: int = 0,
                 times: int = 0, p: float = 0.0, seed: int = 0,
                 mode: str = "raise", hang_s: float = 3600.0):
        self.site = site
        self.raw = raw
        self.every = every
        self.at = at
        self.times = times
        self.p = p
        self.seed = seed
        self.mode = mode
        self.hang_s = hang_s
        self._mu = threading.Lock()
        self._count = 0
        self._rng = random.Random(seed) if p > 0 else None

    def fire(self):
        """Advance the call counter; -> (should_fire, call_number)."""
        with self._mu:
            self._count += 1
            n = self._count
            hit = ((self.every and n % self.every == 0)
                   or (self.at and n == self.at)
                   or (self.times and n <= self.times)
                   or (self._rng is not None
                       and self._rng.random() < self.p))
        return bool(hit), n

    def __repr__(self):
        return f"FaultSpec({self.site}:{self.raw})"


def parse_fault_inject(value: str) -> Dict[str, FaultSpec]:
    """Parse ``FLAGS_fault_inject`` into {site: FaultSpec}.  Raises
    ``ValueError`` on malformed entries so ``set_flags`` rejects a typo'd
    spec up front instead of silently never injecting."""
    specs: Dict[str, FaultSpec] = {}
    for entry in (value or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(
                f"fault-inject entry {entry!r} is not 'site:spec'")
        site, _, body = entry.partition(":")
        site = site.strip()
        if site not in KNOWN_SITES:
            # warn, don't fail: user code may install its own
            # maybe_inject sites — but a TYPO'd runtime site silently
            # never firing is exactly the confusion worth flagging
            import warnings
            warnings.warn(
                f"fault-inject site {site!r} is not a built-in hook "
                f"(known: {', '.join(KNOWN_SITES)}); it will only fire "
                "if something calls maybe_inject() with that name")
        kw: Dict[str, Any] = {}
        for tok in body.split(","):
            tok = tok.strip()
            if not tok:
                continue
            m = _ONCE_RE.match(tok)
            if m:
                kw["at"] = int(m.group(1)) if m.group(1) else 1
                continue
            if tok == "hang":
                kw["mode"] = "hang"
                continue
            if "=" not in tok:
                raise ValueError(
                    f"fault-inject token {tok!r} in {entry!r} not understood"
                    " (expected every=N, once[@N], times=N, p=F, seed=N,"
                    " or hang[=SECS])")
            k, _, v = tok.partition("=")
            k = k.strip()
            if k == "every":
                kw["every"] = int(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "p":
                kw["p"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "hang":
                kw["mode"] = "hang"
                kw["hang_s"] = float(v)
            else:
                raise ValueError(
                    f"unknown fault-inject key {k!r} in {entry!r}")
        if not (kw.get("every") or kw.get("at") or kw.get("times")
                or kw.get("p")):
            raise ValueError(
                f"fault-inject entry {entry!r} has no trigger "
                "(every=/once/times=/p=)")
        if kw.get("every", 0) < 0 or kw.get("times", 0) < 0 or \
                not (0.0 <= kw.get("p", 0.0) <= 1.0):
            raise ValueError(f"fault-inject entry {entry!r} out of range")
        specs[site] = FaultSpec(site, body, **kw)
    return specs


#: live spec table — replaced wholesale by configure(); maybe_inject's
#: fast path is one dict probe against an (almost always) empty dict
_SPECS: Dict[str, FaultSpec] = {}

#: test hook: releasing this event wakes any in-progress injected hang
_HANG_RELEASE = threading.Event()


def configure(value: str) -> None:
    """(Re)load the injection table from a ``FLAGS_fault_inject`` string —
    the flag's side effect calls this, so ``set_flags`` validates eagerly."""
    global _SPECS
    _SPECS = parse_fault_inject(value)
    _HANG_RELEASE.clear()


def release_hangs() -> None:
    """Wake every in-progress injected hang (test teardown hook)."""
    _HANG_RELEASE.set()


def _hang(secs: float) -> None:
    # sleep in small Python-level increments: the watchdog's async raise
    # is delivered at a bytecode boundary, so a hung "step" built from
    # this loop is interruptible the way a C-level hang is not
    end = time.monotonic() + secs
    while time.monotonic() < end and not _HANG_RELEASE.is_set():
        time.sleep(0.02)


def maybe_inject(site: str) -> None:
    """Injection hook: no-op unless ``FLAGS_fault_inject`` names ``site``.
    Fires either an :class:`InjectedFault` or (``hang`` mode) a Python-
    level busy-sleep the watchdog can interrupt."""
    spec = _SPECS.get(site)
    if spec is None:
        return
    hit, n = spec.fire()
    if not hit:
        return
    _FAULT_CTR.inc(1, site=site)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant("fault.injected", "resilience",
                                {"site": site, "call": n,
                                 "mode": spec.mode})
    if spec.mode == "hang":
        _hang(spec.hang_s)
        return
    raise InjectedFault(site, n, spec.raw)


# ---------------------------------------------------------------------------
# retry engine
# ---------------------------------------------------------------------------

def backoff_schedule(attempts: int, base_delay_s: float = 0.05,
                     multiplier: float = 2.0, max_delay_s: float = 2.0,
                     jitter: float = 0.1, seed: int = 0) -> List[float]:
    """The (attempts-1) sleep delays between tries: exponential growth
    capped at ``max_delay_s``, then multiplied by a deterministic jitter in
    ``[1-jitter, 1+jitter]`` drawn from ``random.Random(seed)``.  Pure and
    reproducible — same arguments, same schedule — so tests can assert the
    exact backoff a site will use."""
    if attempts <= 1:
        return []
    rng = random.Random(seed)
    out = []
    d = float(base_delay_s)
    for _ in range(attempts - 1):
        j = 1.0 + jitter * (2.0 * rng.random() - 1.0)
        out.append(min(d, max_delay_s) * j)
        d *= multiplier
    return out


class RetryPolicy:
    """Backoff + budget for one call site.

    ``max_attempts`` counts total tries (1 = no retry); ``deadline_s``
    caps the whole operation — a retry whose backoff sleep would cross the
    deadline is abandoned instead (the ``FLAGS_rpc_deadline`` contract).
    ``seed=None`` derives a stable per-site seed from the site name, so
    two runs of the same workload back off identically."""

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 multiplier: float = 2.0, max_delay_s: float = 2.0,
                 jitter: float = 0.1, deadline_s: Optional[float] = None,
                 seed: Optional[int] = None):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay_s = base_delay_s
        self.multiplier = multiplier
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.seed = seed

    def schedule(self, site: str = "") -> List[float]:
        seed = self.seed if self.seed is not None else \
            zlib.crc32(site.encode())
        return backoff_schedule(self.max_attempts, self.base_delay_s,
                                self.multiplier, self.max_delay_s,
                                self.jitter, seed)

    @classmethod
    def from_flags(cls, site: str) -> "RetryPolicy":
        """The policy the runtime installs at ``site``: PS RPC sites honor
        ``FLAGS_rpc_retry_times`` (retries AFTER the first attempt, the
        gflags meaning) and ``FLAGS_rpc_deadline`` (ms); other sites get a
        conservative 3-attempt default."""
        from .flags import get_flags
        if site.startswith("ps."):
            fl = get_flags(["FLAGS_rpc_retry_times", "FLAGS_rpc_deadline"])
            return cls(max_attempts=1 + int(fl["FLAGS_rpc_retry_times"]),
                       deadline_s=float(fl["FLAGS_rpc_deadline"]) / 1000.0)
        return cls(max_attempts=3)


def retry_call(site: str, fn: Callable, *args,
               policy: Optional[RetryPolicy] = None,
               retryable: Optional[Callable[[BaseException], bool]] = None,
               **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy`` (default:
    ``RetryPolicy.from_flags(site)``).  ``retryable`` filters which
    exceptions earn a retry (default: :func:`is_transient`);
    :class:`HungStepError` and ``KeyboardInterrupt``/``SystemExit`` never
    do.  Counters: each performed retry bumps
    ``paddle_tpu_retry_attempts_total{site}``, an exhausted budget bumps
    ``paddle_tpu_retry_giveups_total{site}``; each backoff sleep is a
    ``retry.backoff`` tracer span."""
    policy = policy or RetryPolicy.from_flags(site)
    check = retryable or is_transient
    delays = None                # built on FIRST failure: the no-failure
    deadline = (time.monotonic() + policy.deadline_s  # hot path pays no
                if policy.deadline_s else None)       # schedule/rng cost
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except (KeyboardInterrupt, SystemExit):
            raise
        except HungStepError:
            raise
        except Exception as e:
            attempt += 1
            if not check(e):
                raise
            if attempt >= policy.max_attempts:
                _GIVEUP_CTR.inc(1, site=site)
                raise
            if delays is None:
                delays = policy.schedule(site)
            delay = delays[attempt - 1]
            if deadline is not None and \
                    time.monotonic() + delay > deadline:
                _GIVEUP_CTR.inc(1, site=site)
                raise RuntimeError(
                    f"{site}: retry deadline exceeded after {attempt} "
                    f"attempt(s) (policy deadline "
                    f"{policy.deadline_s}s): {e}") from e
            _RETRY_CTR.inc(1, site=site)
            with _monitor.TRACER.span("retry.backoff", "resilience",
                                      site=site, attempt=attempt,
                                      delay_s=round(delay, 4)):
                time.sleep(delay)


# ---------------------------------------------------------------------------
# hung-step watchdog
# ---------------------------------------------------------------------------

def dump_state(reason: str, site: str = "") -> str:
    """Write a watchdog dump — every thread's Python stack, the metrics
    registry totals, and the most recent telemetry-ring spans — to
    ``FLAGS_watchdog_dump_dir`` (default: the system temp dir).  Returns
    the file path (named ``paddle_tpu_watchdog_<pid>_<ms>.txt``).

    Format: a ``=== watchdog dump ===`` header (reason, site, pid, time),
    one ``--- thread <name> (<ident>) ---`` stack section per live
    thread, a ``--- metrics ---`` JSON object of counter totals, and a
    ``--- trace (last 200 events) ---`` JSON array of chrome-trace
    events."""
    from .flags import get_flags
    d = get_flags("FLAGS_watchdog_dump_dir")["FLAGS_watchdog_dump_dir"] \
        or tempfile.gettempdir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"paddle_tpu_watchdog_{os.getpid()}_{int(time.time()*1e3)}.txt")
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = ["=== watchdog dump ===",
             f"reason: {reason}",
             f"site: {site or '<unknown>'}",
             f"pid: {os.getpid()}",
             f"time: {time.strftime('%Y-%m-%dT%H:%M:%S')}",
             ""]
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        lines.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
        lines.append("")
    lines.append("--- metrics ---")
    try:
        lines.append(json.dumps(_monitor.counter_totals(), indent=1,
                                sort_keys=True))
    except Exception as e:        # the dump must never fail the dumper
        lines.append(f"<metrics unavailable: {e}>")
    lines.append("")
    lines.append("--- trace (last 200 events) ---")
    try:
        lines.append(json.dumps(_monitor.TRACER.chrome_events()[-200:]))
    except Exception as e:
        lines.append(f"<trace unavailable: {e}>")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def _async_raise(tid: int, exc_type) -> None:
    """Deliver (or, with ``exc_type=None``, cancel) an async exception in
    the thread with ident ``tid`` — lands at its next bytecode boundary."""
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid),
        ctypes.py_object(exc_type) if exc_type is not None else None)


class Watchdog:
    """Deadline supervisor for watched sections (executor dispatch, fetch
    materialization).  One daemon monitor thread tracks every active
    ``watch()``; on expiry it writes a :func:`dump_state` file, bumps
    ``paddle_tpu_watchdog_fired_total{site}``, and async-raises
    :class:`HungStepError` in the hung thread.  ``timeout_s <= 0``
    (the default) disables everything — ``watch()`` is then one float
    compare."""

    def __init__(self):
        self._cv = threading.Condition(threading.Lock())
        self._watches: Dict[int, dict] = {}
        self._ids = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self.timeout_s = 0.0

    def set_timeout(self, secs: float) -> None:
        self.timeout_s = float(secs)
        with self._cv:
            self._cv.notify()

    @contextlib.contextmanager
    def watch(self, site: str):
        t = self.timeout_s
        if t <= 0:
            yield
            return
        entry = {"tid": threading.get_ident(), "site": site,
                 "deadline": time.monotonic() + t, "timeout": t,
                 "fired": False, "dump": None}
        with self._cv:
            wid = next(self._ids)
            self._watches[wid] = entry
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="pt-watchdog")
                self._thread.start()
            self._cv.notify()
        delivered = False
        try:
            yield
        except HungStepError as he:
            delivered = True
            if entry["fired"]:
                # enrich the bare async-raised error with the diagnosis
                raise HungStepError(self._msg(entry)) from he
            raise
        finally:
            with self._cv:
                self._watches.pop(wid, None)
            if entry["fired"] and not delivered:
                # the watched call ended (returned, or raised its OWN
                # error) after the deadline fired but before the async
                # exception landed — withdraw it on EVERY exit path, or
                # the stale HungStepError detonates at some arbitrary
                # later bytecode in this thread, masking the real outcome
                # (best effort: delivery racing this cancel still raises
                # HungStepError, just possibly a frame later)
                _async_raise(entry["tid"], None)
        if entry["fired"]:
            raise HungStepError(self._msg(entry))

    @staticmethod
    def _msg(entry: dict) -> str:
        where = entry["dump"] or \
            "<dump still writing — check FLAGS_watchdog_dump_dir>"
        return (f"step hung: {entry['site']!r} exceeded "
                f"FLAGS_watchdog_timeout_s={entry['timeout']}s; thread "
                f"stacks + telemetry dumped to {where}")

    def _loop(self):
        while True:
            with self._cv:
                now = time.monotonic()
                pending = [(w, e) for w, e in self._watches.items()
                           if not e["fired"]]
                expired = [(w, e) for w, e in pending
                           if e["deadline"] <= now]
                for _, e in expired:
                    e["fired"] = True
                if not expired:
                    nxt = min((e["deadline"] for _, e in pending),
                              default=now + 5.0)
                    self._cv.wait(timeout=max(nxt - now, 0.02))
                    continue
            for wid, e in expired:    # I/O outside the lock
                try:
                    e["dump"] = dump_state(
                        f"watched section exceeded {e['timeout']}s",
                        site=e["site"])
                except Exception:
                    e["dump"] = "<dump failed>"
                _WATCHDOG_CTR.inc(1, site=e["site"])
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "watchdog.fired", "resilience",
                        {"site": e["site"], "dump": e["dump"]})
                with self._cv:
                    # only async-raise while the watch is still
                    # registered: if the "hung" call returned during the
                    # dump, the exiting watch() raises directly — an
                    # unconditional raise here could detonate at an
                    # arbitrary later bytecode in that thread
                    if wid in self._watches:
                        _async_raise(e["tid"], HungStepError)


WATCHDOG = Watchdog()


# ---------------------------------------------------------------------------
# preemption guard + resume
# ---------------------------------------------------------------------------

class PreemptionGuard:
    """Graceful SIGTERM/SIGINT drain for a training loop.

    ::

        ckpt = CheckpointManager(ckpt_dir)
        start = resume_or_init(ckpt, exe, startup_program=startup,
                               main_program=main)
        with PreemptionGuard(ckpt, executor=exe, program=main) as guard:
            for step in range(start, total_steps):
                exe.run(main, feed=batch(step), fetch_list=[loss])
                guard.completed_step(step + 1)
                if guard.preempted:
                    break
        # guard exit (preempted): drain in-flight steps, force an
        # emergency checkpoint at the last complete step, export
        # telemetry, SystemExit(exit_code)

    The signal handler only sets a flag — checkpointing from inside a
    handler could snapshot a half-dispatched step.  The loop polls
    ``guard.preempted`` at step boundaries (where the scope is a complete,
    consistent state) and breaks; everything irreversible happens on the
    normal exit path.  Handlers are restored on exit.  Signal installation
    requires the main thread; elsewhere the guard still works via
    :meth:`trigger` (and warns once).
    """

    def __init__(self, checkpoint=None, executor=None, program=None,
                 scope=None, signals=(signal.SIGTERM, signal.SIGINT),
                 export_dir: Optional[str] = None,
                 exit_on_preempt: bool = True, exit_code: int = 0):
        self.checkpoint = checkpoint
        self.executor = executor
        self.program = program
        self.scope = scope
        self.signals = tuple(signals)
        self.export_dir = export_dir
        self.exit_on_preempt = exit_on_preempt
        self.exit_code = exit_code
        self._preempted = threading.Event()
        self._signum = signal.SIGTERM
        self._noted = False
        self._last_step: Optional[int] = None
        self._old: Dict[int, Any] = {}

    # -- signal plumbing -----------------------------------------------------
    def _handler(self, signum, frame):
        self.trigger(signum)

    def trigger(self, signum: int = signal.SIGTERM) -> None:
        """Record a preemption request (the signal handler body; callable
        directly from tests or cluster-notification hooks).

        LOCK-FREE on purpose: this runs on the main thread *interrupting
        its own frame*, which may be inside a tracer/metric critical
        section — taking any of those non-reentrant locks here would
        self-deadlock the process at the exact moment it must drain.
        Event.set() alone is safe; the counter/tracer bumps happen later,
        on the drain/exit path (:meth:`_note_signal`)."""
        self._signum = signum
        self._preempted.set()

    def _note_signal(self) -> None:
        """Deferred observability for the signal: runs on the normal exit
        path, where taking the metric/tracer locks is safe."""
        if self._noted or not self._preempted.is_set():
            return
        self._noted = True
        signum = self._signum
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        _PREEMPT_CTR.inc(1, signal=name)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant("preemption.signal", "resilience",
                                    {"signal": int(signum)})

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    def completed_step(self, step: int) -> None:
        """Mark ``step`` steps as fully complete (scope state consistent
        through that step) — the emergency checkpoint saves at this index."""
        self._last_step = int(step)

    # -- drain + emergency checkpoint ---------------------------------------
    def drain(self) -> None:
        """Block until every in-flight dispatched step has retired (the
        executor's throttle queue) — after this the scope holds fully
        computed values."""
        if self.executor is not None and hasattr(self.executor, "drain"):
            with _monitor.TRACER.span("preemption.drain", "resilience"):
                self.executor.drain()

    def emergency_checkpoint(self) -> Optional[int]:
        """Drain, then force-save the last complete step; returns the step
        saved (None when no checkpoint manager / no completed step)."""
        self.drain()
        if self.checkpoint is None or self._last_step is None:
            return None
        with _monitor.TRACER.span("preemption.checkpoint", "resilience",
                                  step=self._last_step):
            self.checkpoint.save(self._last_step, program=self.program,
                                 scope=self.scope, force=True)
            # the save may be async (orbax): the process is about to exit,
            # so it must land on disk NOW
            wait = getattr(self.checkpoint, "_mgr", None)
            if wait is not None and hasattr(wait, "wait_until_finished"):
                wait.wait_until_finished()
        return self._last_step

    # -- context manager -----------------------------------------------------
    def __enter__(self):
        for s in self.signals:
            try:
                self._old[s] = signal.signal(s, self._handler)
            except ValueError:      # not the main thread
                import warnings
                warnings.warn(
                    "PreemptionGuard: cannot install signal handlers "
                    "outside the main thread; use guard.trigger()")
                break
        return self

    def __exit__(self, et, ev, tb):
        try:
            # the emergency path runs with OUR handlers still installed:
            # a scheduler's follow-up SIGTERM (or a second Ctrl-C) during
            # the drain/save just re-sets the already-set flag instead of
            # killing the process mid-emergency-checkpoint
            if et is None and self.preempted:
                self.emergency_checkpoint()
                if self.export_dir:
                    try:
                        _monitor.export(self.export_dir)
                    except Exception:   # telemetry must not block the exit
                        pass
        finally:
            for s, old in self._old.items():
                try:
                    signal.signal(s, old)
                except ValueError:
                    pass
            self._old.clear()
            self._note_signal()
        if et is None and self.preempted and self.exit_on_preempt:
            raise SystemExit(self.exit_code)
        return False


def resume_or_init(checkpoint, executor, startup_program=None,
                   main_program=None, scope=None) -> int:
    """Restart a training loop from the last complete checkpoint.

    Runs the startup program (vars must exist before a restore can fill
    them — and a cold start needs its initializers anyway), then restores
    the latest checkpoint when one exists.  Returns the number of COMPLETE
    steps — the loop resumes at that index, so an interrupted run's loss
    trajectory continues exactly where the emergency save left it::

        start = resume_or_init(ckpt, exe, startup_program=startup,
                               main_program=main)
        for step in range(start, total_steps):
            ...
    """
    from .framework.core import default_startup_program
    startup = startup_program or default_startup_program()
    executor.run(startup, scope=scope)
    step = checkpoint.latest_step()
    if step is None:
        return 0
    checkpoint.restore(step, program=main_program, scope=scope)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant("preemption.resume", "resilience",
                                {"step": int(step)})
    return int(step)


# ---------------------------------------------------------------------------
# flag sync (mirrors monitor._sync_from_flags: whichever of the two
# modules imports second sees the other's already-bootstrapped values)
# ---------------------------------------------------------------------------

def _sync_from_flags():
    try:
        from .flags import get_flags
        fl = get_flags(["FLAGS_fault_inject", "FLAGS_watchdog_timeout_s"])
    except Exception:           # flags mid-bootstrap: side effects re-sync
        return
    if fl["FLAGS_fault_inject"]:
        configure(str(fl["FLAGS_fault_inject"]))
    WATCHDOG.set_timeout(float(fl["FLAGS_watchdog_timeout_s"]))


_sync_from_flags()
