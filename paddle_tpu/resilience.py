"""Fault-tolerant training runtime: fault injection, retry/backoff,
preemption drain, and a hung-step watchdog.

The reference Fluid stack shipped a real failure story — ``FLAGS_rpc_retry_
times``/``FLAGS_rpc_deadline`` on the PS RPC plane (``grpc_client.cc``
retries) and ``checkpoint_notify`` snapshots — but until this layer the
rebuild only carried the flags.  On TPU the dominant failure mode is
preemption and transient infra flake, so every layer that talks to the
outside world (PS RPCs, the dataloader producer thread, XLA compiles,
checkpoint writes, executor dispatch) gets a supervision story here:

- **Fault injection** (``FLAGS_fault_inject="site:spec[;site:spec...]"``):
  deterministic, flag-driven fault hooks compiled into the dataloader
  producer, the compiler's graph-pass path, executor dispatch, checkpoint
  writes, and every ``PSClient`` RPC.  Spec grammar (comma-joined keys)::

      ps.put:every=3              # every 3rd call raises
      compile:once@2              # exactly the 2nd call (also once@step2)
      dataloader.produce:p=0.1,seed=7   # Bernoulli, deterministic stream
      checkpoint.write:times=2    # the first 2 calls
      executor.dispatch:once,hang=30    # 2nd form: hang instead of raise

  Injected faults raise :class:`InjectedFault` (transient by contract) and
  bump ``paddle_tpu_fault_injected_total{site=...}`` — so a test can assert
  the exact number of faults the spec implies.

- **Retry engine**: :func:`retry_call` runs a callable under a
  :class:`RetryPolicy` (exponential backoff + deterministic jitter, capped
  by an optional deadline).  Checkpoint writes, transient compile
  failures, and the PS injection plane ride it; PS *transport* retries
  belong to the native client (which already implements the
  ``FLAGS_rpc_retry_times`` loop and alone knows which ops are safe to
  replay) — the flags' side effects mirror
  ``FLAGS_rpc_retry_times``/``FLAGS_rpc_deadline`` into the env so
  ``set_flags`` finally governs that loop.  Every retry bumps
  ``paddle_tpu_retry_attempts_total{site=...}`` and records a
  ``retry.backoff`` tracer span; exhausted budgets bump
  ``paddle_tpu_retry_giveups_total{site=...}``.

- **Preemption drain** (:class:`PreemptionGuard`): a context manager that
  installs SIGTERM/SIGINT handlers; the training loop polls
  ``guard.preempted`` at step boundaries (the handler only sets a flag —
  never checkpoints mid-step), and guard exit drains the executor's
  in-flight throttle queue, writes an emergency ``CheckpointManager``
  checkpoint at the last *complete* step, exports telemetry, and exits
  cleanly.  :func:`resume_or_init` restarts a ``train_from_dataset``-style
  loop from the last complete step.

- **Hung-step watchdog** (``FLAGS_watchdog_timeout_s``): executor dispatch
  and fetch materialization run under ``WATCHDOG.watch(site)``; a step
  exceeding the deadline gets all thread stacks + the metrics registry +
  the telemetry ring dumped to ``FLAGS_watchdog_dump_dir`` and a
  :class:`HungStepError` naming the dump file raised in the hung thread —
  a diagnosable failure instead of a silent CI timeout.  (The async raise
  lands at the next Python bytecode boundary; a thread hung inside a C
  call still gets the dump immediately and the error on return.)

Every recovery action is observable through the PR 2 registry/tracer, so
the layer is testable end to end: inject faults, assert on exported
counters (``tools/resilience_smoke.py`` is the CI gate).
"""

from __future__ import annotations

import contextlib
import ctypes
import itertools
import json
import os
import random
import re
import signal
import sys
import tempfile
import threading
import time
import traceback
import zlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import monitor as _monitor

__all__ = [
    "InjectedFault", "HungStepError", "CircuitOpenError",
    "is_transient", "mark_transient",
    "FaultSpec", "parse_fault_inject", "configure", "maybe_inject",
    "backoff_schedule", "RetryPolicy", "retry_call", "CircuitBreaker",
    "CheckpointDaemon", "PreemptionGuard", "resume_or_init",
    "Watchdog", "WATCHDOG", "dump_state",
]

# ---------------------------------------------------------------------------
# metrics (one family per recovery action; per-site label series)
# ---------------------------------------------------------------------------

_FAULT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_fault_injected_total",
    "faults fired by the FLAGS_fault_inject framework", ("site",))
_RETRY_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_retry_attempts_total",
    "retries performed after a transient failure (first attempts do not "
    "count — a clean run exports 0)", ("site",))
_GIVEUP_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_retry_giveups_total",
    "operations abandoned after exhausting their retry/deadline budget",
    ("site",))
_WATCHDOG_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_watchdog_fired_total",
    "hung-step watchdog expirations (each writes a stack+telemetry dump)",
    ("site",))
_PREEMPT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_preemption_signals_total",
    "SIGTERM/SIGINT deliveries observed by a PreemptionGuard", ("signal",))
_CIRCUIT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_retry_circuit_open_total",
    "calls failed fast by an open circuit breaker (no RPC attempted, no "
    "backoff paid)", ("site",))
#: live breaker state per named breaker (PS clients name theirs by
#: endpoint): 0=closed, 1=half_open (cool-down elapsed, probe pending or
#: in flight), 2=open.  Transitions were previously counters only —
#: invisible mid-flight; this gauge is the live view a dashboard needs.
BREAKER_STATE = {"closed": 0, "half_open": 1, "open": 2}
_BREAKER_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_circuit_breaker_state",
    "live circuit-breaker state per named breaker (PS: per endpoint): "
    "0=closed, 1=half_open (a probe call was claimed), 2=open (a "
    "cooled-down breaker stays 2 until some call claims the probe)",
    ("endpoint",))


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """A deterministic fault fired by ``FLAGS_fault_inject`` — transient by
    contract, so the retry engine absorbs it wherever a retry policy is
    installed (that asymmetry IS the test: sites with retries complete,
    sites without surface the fault)."""

    pt_transient = True

    def __init__(self, site: str, call_n: int, spec: str):
        super().__init__(
            f"injected fault at {site!r} (call #{call_n}, spec {spec!r})")
        self.site = site
        self.call_n = call_n


class HungStepError(RuntimeError):
    """Raised by the watchdog when a watched step exceeds
    ``FLAGS_watchdog_timeout_s``.  Never retryable: the hang already
    consumed the deadline, and the dump file is the diagnosis."""


class CircuitOpenError(RuntimeError):
    """Raised (fail-fast, no RPC attempted) while a circuit breaker is
    open: the endpoint already burned a full retry budget, so re-paying
    the backoff per call would only stall the training loop.  Not
    transient — retrying the rejection is the exact behavior the breaker
    exists to stop; the half-open probe re-tests the endpoint instead."""

    def __init__(self, name: str, remaining_s: float):
        super().__init__(
            f"circuit breaker for {name!r} is open "
            f"({remaining_s:.2f}s of FLAGS_rpc_circuit_break_secs "
            "cool-down remaining); failing fast")
        self.name = name
        self.remaining_s = remaining_s


def mark_transient(e: BaseException) -> BaseException:
    """Tag an exception as transient so :func:`is_transient` callers
    (compile retries, user-level ``retry_call`` policies) treat it as
    retryable."""
    e.pt_transient = True
    return e


def is_transient(e: BaseException) -> bool:
    return bool(getattr(e, "pt_transient", False))


# ---------------------------------------------------------------------------
# fault-injection framework
# ---------------------------------------------------------------------------

#: the sites the runtime has hooks at (documented contract; parsing warns
#: on unknown sites rather than failing — forward-compat with user hooks)
KNOWN_SITES = (
    "ps.put", "ps.get", "ps.push_dense", "ps.push_sparse", "ps.get_rows",
    "ps.put_typed", "ps.get_typed", "ps.push_typed",
    "dataloader.produce", "compile", "executor.dispatch",
    "fetch.materialize", "checkpoint.write", "serving.decode_step",
    # fires at the top of every collective shard_map dispatch (before
    # the pre-collective timestamp exchange) — hang mode makes THIS rank
    # the straggler its peers' wait decomposition must attribute
    # (tools/comms_smoke.py's drill)
    "collective.launch",
    # value-domain drill: corrupts one float rw persistable with NaN
    # after a dispatched step — the numerics plane must DETECT it (the
    # hook itself never raises out of the executor)
    "numerics.poison",
    # OOM drill: fires inside the executor's dispatch try block, so the
    # raised fault runs the SAME hbm.oom_forensics path a real
    # RESOURCE_EXHAUSTED does (dump + paddle_tpu_oom_total + memory.oom
    # instant + trigger:"oom" profiler window — tools/hbm_smoke.py)
    "memory.oom",
    # fires inside ContinuousBatcher._dispatch before the batch executes
    # (transient → absorbed by the scheduler's retry budget; hang mode
    # trips the serving watchdog and fails the batch)
    "serving.batch_dispatch",
    # fleet chaos sites (tools/fleet_smoke.py): a router forward attempt
    # (reroute drill), one coordinator frame service (torn-frame /
    # dropped-connection drill), one client heartbeat send (liveness
    # false-positive drill — the beat is skipped, not the rank killed)
    "router.forward",
    "coordinator.frame",
    "replica.heartbeat",
    # autoscaler control plane (tools/fleet_smoke.py --scenario scale):
    # decide fires at the top of every controller tick (an injected
    # fault skips the tick, never kills the loop); spawn fires inside
    # the spawn worker before the launcher runs (the controller must
    # back off and re-shed); retire fires before the drain-path retire
    # (the un-SIGTERM'd replica self-heals back to "up" on its next
    # reply)
    "autoscaler.decide",
    "autoscaler.spawn",
    "autoscaler.retire",
)

_ONCE_RE = re.compile(r"^once(?:@(?:step)?(\d+))?$")


class FaultSpec:
    """One site's parsed injection spec + its thread-safe call counter."""

    def __init__(self, site: str, raw: str, every: int = 0, at: int = 0,
                 times: int = 0, p: float = 0.0, seed: int = 0,
                 mode: str = "raise", hang_s: float = 3600.0):
        self.site = site
        self.raw = raw
        self.every = every
        self.at = at
        self.times = times
        self.p = p
        self.seed = seed
        self.mode = mode
        self.hang_s = hang_s
        self._mu = threading.Lock()
        self._count = 0
        self._rng = random.Random(seed) if p > 0 else None

    def fire(self):
        """Advance the call counter; -> (should_fire, call_number)."""
        with self._mu:
            self._count += 1
            n = self._count
            hit = ((self.every and n % self.every == 0)
                   or (self.at and n == self.at)
                   or (self.times and n <= self.times)
                   or (self._rng is not None
                       and self._rng.random() < self.p))
        return bool(hit), n

    def __repr__(self):
        return f"FaultSpec({self.site}:{self.raw})"


def parse_fault_inject(value: str) -> Dict[str, FaultSpec]:
    """Parse ``FLAGS_fault_inject`` into {site: FaultSpec}.  Raises
    ``ValueError`` on malformed entries so ``set_flags`` rejects a typo'd
    spec up front instead of silently never injecting."""
    specs: Dict[str, FaultSpec] = {}
    for entry in (value or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(
                f"fault-inject entry {entry!r} is not 'site:spec'")
        site, _, body = entry.partition(":")
        site = site.strip()
        if site not in KNOWN_SITES:
            # warn, don't fail: user code may install its own
            # maybe_inject sites — but a TYPO'd runtime site silently
            # never firing is exactly the confusion worth flagging
            import warnings
            warnings.warn(
                f"fault-inject site {site!r} is not a built-in hook "
                f"(known: {', '.join(KNOWN_SITES)}); it will only fire "
                "if something calls maybe_inject() with that name")
        kw: Dict[str, Any] = {}
        for tok in body.split(","):
            tok = tok.strip()
            if not tok:
                continue
            m = _ONCE_RE.match(tok)
            if m:
                kw["at"] = int(m.group(1)) if m.group(1) else 1
                continue
            if tok == "hang":
                kw["mode"] = "hang"
                continue
            if "=" not in tok:
                raise ValueError(
                    f"fault-inject token {tok!r} in {entry!r} not understood"
                    " (expected every=N, once[@N], times=N, p=F, seed=N,"
                    " or hang[=SECS])")
            k, _, v = tok.partition("=")
            k = k.strip()
            if k == "every":
                kw["every"] = int(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "p":
                kw["p"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "hang":
                kw["mode"] = "hang"
                kw["hang_s"] = float(v)
            else:
                raise ValueError(
                    f"unknown fault-inject key {k!r} in {entry!r}")
        if not (kw.get("every") or kw.get("at") or kw.get("times")
                or kw.get("p")):
            raise ValueError(
                f"fault-inject entry {entry!r} has no trigger "
                "(every=/once/times=/p=)")
        if kw.get("every", 0) < 0 or kw.get("times", 0) < 0 or \
                not (0.0 <= kw.get("p", 0.0) <= 1.0):
            raise ValueError(f"fault-inject entry {entry!r} out of range")
        specs[site] = FaultSpec(site, body, **kw)
    return specs


#: live spec table — replaced wholesale by configure(); maybe_inject's
#: fast path is one dict probe against an (almost always) empty dict
_SPECS: Dict[str, FaultSpec] = {}

#: test hook: releasing this event wakes any in-progress injected hang
_HANG_RELEASE = threading.Event()


def configure(value: str) -> None:
    """(Re)load the injection table from a ``FLAGS_fault_inject`` string —
    the flag's side effect calls this, so ``set_flags`` validates eagerly."""
    global _SPECS
    _SPECS = parse_fault_inject(value)
    _HANG_RELEASE.clear()


def release_hangs() -> None:
    """Wake every in-progress injected hang (test teardown hook)."""
    _HANG_RELEASE.set()


def _hang(secs: float) -> None:
    # sleep in small Python-level increments: the watchdog's async raise
    # is delivered at a bytecode boundary, so a hung "step" built from
    # this loop is interruptible the way a C-level hang is not
    end = time.monotonic() + secs
    while time.monotonic() < end and not _HANG_RELEASE.is_set():
        time.sleep(0.02)


def maybe_inject(site: str) -> None:
    """Injection hook: no-op unless ``FLAGS_fault_inject`` names ``site``.
    Fires either an :class:`InjectedFault` or (``hang`` mode) a Python-
    level busy-sleep the watchdog can interrupt."""
    spec = _SPECS.get(site)
    if spec is None:
        return
    hit, n = spec.fire()
    if not hit:
        return
    _FAULT_CTR.inc(1, site=site)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant("fault.injected", "resilience",
                                {"site": site, "call": n,
                                 "mode": spec.mode})
    if spec.mode == "hang":
        _hang(spec.hang_s)
        return
    raise InjectedFault(site, n, spec.raw)


# ---------------------------------------------------------------------------
# retry engine
# ---------------------------------------------------------------------------

def backoff_schedule(attempts: int, base_delay_s: float = 0.05,
                     multiplier: float = 2.0, max_delay_s: float = 2.0,
                     jitter: float = 0.1, seed: int = 0) -> List[float]:
    """The (attempts-1) sleep delays between tries: exponential growth
    capped at ``max_delay_s``, then multiplied by a deterministic jitter in
    ``[1-jitter, 1+jitter]`` drawn from ``random.Random(seed)``.  Pure and
    reproducible — same arguments, same schedule — so tests can assert the
    exact backoff a site will use."""
    if attempts <= 1:
        return []
    rng = random.Random(seed)
    out = []
    d = float(base_delay_s)
    for _ in range(attempts - 1):
        j = 1.0 + jitter * (2.0 * rng.random() - 1.0)
        out.append(min(d, max_delay_s) * j)
        d *= multiplier
    return out


class RetryPolicy:
    """Backoff + budget for one call site.

    ``max_attempts`` counts total tries (1 = no retry); ``deadline_s``
    caps the whole operation — a retry whose backoff sleep would cross the
    deadline is abandoned instead (the ``FLAGS_rpc_deadline`` contract).
    ``seed=None`` derives a stable per-site seed from the site name, so
    two runs of the same workload back off identically."""

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 multiplier: float = 2.0, max_delay_s: float = 2.0,
                 jitter: float = 0.1, deadline_s: Optional[float] = None,
                 seed: Optional[int] = None):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay_s = base_delay_s
        self.multiplier = multiplier
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.seed = seed

    def schedule(self, site: str = "") -> List[float]:
        seed = self.seed if self.seed is not None else \
            zlib.crc32(site.encode())
        return backoff_schedule(self.max_attempts, self.base_delay_s,
                                self.multiplier, self.max_delay_s,
                                self.jitter, seed)

    @classmethod
    def from_flags(cls, site: str) -> "RetryPolicy":
        """The policy the runtime installs at ``site``: PS RPC sites honor
        ``FLAGS_rpc_retry_times`` (retries AFTER the first attempt, the
        gflags meaning) and ``FLAGS_rpc_deadline`` (ms); other sites get a
        conservative 3-attempt default."""
        from .flags import get_flags
        if site.startswith("ps."):
            fl = get_flags(["FLAGS_rpc_retry_times", "FLAGS_rpc_deadline"])
            return cls(max_attempts=1 + int(fl["FLAGS_rpc_retry_times"]),
                       deadline_s=float(fl["FLAGS_rpc_deadline"]) / 1000.0)
        return cls(max_attempts=3)


def retry_call(site: str, fn: Callable, *args,
               policy: Optional[RetryPolicy] = None,
               retryable: Optional[Callable[[BaseException], bool]] = None,
               **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy`` (default:
    ``RetryPolicy.from_flags(site)``).  ``retryable`` filters which
    exceptions earn a retry (default: :func:`is_transient`);
    :class:`HungStepError` and ``KeyboardInterrupt``/``SystemExit`` never
    do.  Counters: each performed retry bumps
    ``paddle_tpu_retry_attempts_total{site}``, an exhausted budget bumps
    ``paddle_tpu_retry_giveups_total{site}``; each backoff sleep is a
    ``retry.backoff`` tracer span."""
    policy = policy or RetryPolicy.from_flags(site)
    check = retryable or is_transient
    delays = None                # built on FIRST failure: the no-failure
    deadline = (time.monotonic() + policy.deadline_s  # hot path pays no
                if policy.deadline_s else None)       # schedule/rng cost
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except (KeyboardInterrupt, SystemExit):
            raise
        except HungStepError:
            raise
        except Exception as e:
            attempt += 1
            if not check(e):
                raise
            if attempt >= policy.max_attempts:
                _GIVEUP_CTR.inc(1, site=site)
                raise
            if delays is None:
                delays = policy.schedule(site)
            delay = delays[attempt - 1]
            if deadline is not None and \
                    time.monotonic() + delay > deadline:
                _GIVEUP_CTR.inc(1, site=site)
                raise RuntimeError(
                    f"{site}: retry deadline exceeded after {attempt} "
                    f"attempt(s) (policy deadline "
                    f"{policy.deadline_s}s): {e}") from e
            _RETRY_CTR.inc(1, site=site)
            with _monitor.TRACER.span("retry.backoff", "resilience",
                                      site=site, attempt=attempt,
                                      delay_s=round(delay, 4)):
                time.sleep(delay)


# ---------------------------------------------------------------------------
# circuit breaker (per-endpoint fail-fast after retry give-up)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Classic closed → open → half-open breaker around a flaky endpoint.

    A retry GIVE-UP (budget exhausted on transient failures — never a
    deterministic server verdict) opens the breaker; while open, callers
    fail fast with :class:`CircuitOpenError` instead of re-paying the full
    backoff schedule per call.  After ``FLAGS_rpc_circuit_break_secs`` of
    cool-down, exactly ONE call is let through as the half-open probe: its
    success re-closes the breaker, its give-up re-opens it (concurrent
    calls during the probe keep failing fast).  ``cooldown_s=None`` reads
    the flag per check, so ``set_flags`` retunes live breakers; a cool-down
    of 0 disables the breaker entirely.

    Every fail-fast rejection bumps
    ``paddle_tpu_retry_circuit_open_total{site}`` and records a
    ``retry.circuit_open`` tracer instant — a storm of rejections in the
    metrics IS the outage report.
    """

    def __init__(self, name: str = "", cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._cooldown = cooldown_s
        self._clock = clock
        self._mu = threading.Lock()
        self._opened_at: Optional[float] = None
        self._probing = False
        # live state gauge, bound once per NAMED breaker (anonymous
        # test breakers stay out of the registry); transitions publish
        # through _publish so the gauge can never lag the state
        self._state_cell = (_BREAKER_GAUGE.labels(endpoint=name)
                            if name else None)
        self._publish("closed")

    def _publish(self, state: str) -> None:
        if self._state_cell is not None:
            self._state_cell.set(BREAKER_STATE[state])

    def cooldown_s(self) -> float:
        if self._cooldown is not None:
            return float(self._cooldown)
        from .flags import get_flags
        return float(get_flags("FLAGS_rpc_circuit_break_secs")
                     ["FLAGS_rpc_circuit_break_secs"])

    @property
    def state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` (cool-down elapsed: the
        next check claims the probe)."""
        cd = self.cooldown_s()
        with self._mu:
            if self._opened_at is None or cd <= 0:
                return "closed"
            if self._probing or \
                    self._clock() - self._opened_at >= cd:
                return "half_open"
            return "open"

    def check(self, site: str = "") -> None:
        """Gate one call: no-op when closed (or disabled); claims the
        half-open probe when cooled down; otherwise raises
        :class:`CircuitOpenError` without touching the endpoint."""
        cd = self.cooldown_s()
        if cd <= 0:
            return
        with self._mu:
            if self._opened_at is None:
                return
            elapsed = self._clock() - self._opened_at
            if not self._probing and elapsed >= cd:
                self._probing = True        # this caller IS the probe
                self._publish("half_open")
                return
            remaining = max(cd - elapsed, 0.0)
        label = site or self.name or "<unnamed>"
        _CIRCUIT_CTR.inc(1, site=label)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "retry.circuit_open", "resilience",
                {"site": label, "breaker": self.name,
                 "remaining_s": round(remaining, 3)})
        raise CircuitOpenError(self.name or label, remaining)

    def record_success(self) -> None:
        """A call (probe or normal) completed: close the breaker."""
        with self._mu:
            self._opened_at = None
            self._probing = False
            self._publish("closed")

    def record_giveup(self) -> None:
        """A retry budget was exhausted: (re)open the breaker and restart
        the cool-down clock."""
        with self._mu:
            self._opened_at = self._clock()
            self._probing = False
            self._publish("open")


# ---------------------------------------------------------------------------
# hung-step watchdog
# ---------------------------------------------------------------------------

def dump_state(reason: str, site: str = "") -> str:
    """Write a watchdog dump — every thread's Python stack, the metrics
    registry totals, and the most recent telemetry-ring spans — to
    ``FLAGS_watchdog_dump_dir`` (default: the system temp dir).  Returns
    the file path (named ``paddle_tpu_watchdog_<pid>_<ms>.txt``).

    Format: a ``=== watchdog dump ===`` header (reason, site, pid, time),
    one ``--- thread <name> (<ident>) ---`` stack section per live
    thread, a ``--- metrics ---`` JSON object of counter totals, and a
    ``--- trace (last 200 events) ---`` JSON array of chrome-trace
    events."""
    from .flags import get_flags
    d = get_flags("FLAGS_watchdog_dump_dir")["FLAGS_watchdog_dump_dir"] \
        or tempfile.gettempdir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"paddle_tpu_watchdog_{os.getpid()}_{int(time.time()*1e3)}.txt")
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = ["=== watchdog dump ===",
             f"reason: {reason}",
             f"site: {site or '<unknown>'}",
             f"pid: {os.getpid()}",
             f"time: {time.strftime('%Y-%m-%dT%H:%M:%S')}",
             ""]
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        lines.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
        lines.append("")
    lines.append("--- metrics ---")
    try:
        lines.append(json.dumps(_monitor.counter_totals(), indent=1,
                                sort_keys=True))
    except Exception as e:        # the dump must never fail the dumper
        lines.append(f"<metrics unavailable: {e}>")
    lines.append("")
    lines.append("--- trace (last 200 events) ---")
    try:
        lines.append(json.dumps(_monitor.TRACER.chrome_events()[-200:]))
    except Exception as e:
        lines.append(f"<trace unavailable: {e}>")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def _async_raise(tid: int, exc_type) -> None:
    """Deliver (or, with ``exc_type=None``, cancel) an async exception in
    the thread with ident ``tid`` — lands at its next bytecode boundary."""
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid),
        ctypes.py_object(exc_type) if exc_type is not None else None)


class Watchdog:
    """Deadline supervisor for watched sections (executor dispatch, fetch
    materialization).  One daemon monitor thread tracks every active
    ``watch()``; on expiry it writes a :func:`dump_state` file, bumps
    ``paddle_tpu_watchdog_fired_total{site}``, and async-raises
    :class:`HungStepError` in the hung thread.  ``timeout_s <= 0``
    (the default) disables everything — ``watch()`` is then one float
    compare.

    C-level hangs: the async raise only lands at a Python bytecode
    boundary, so a thread stuck inside a C call (an XLA execute that
    never returns) gets the dump but not the error.  Two extra tiers
    cover it: every armed watch also schedules
    ``faulthandler.dump_traceback_later`` (its C-level watchdog thread
    dumps every stack even when the GIL never comes back), and with
    ``FLAGS_watchdog_escalate=abort`` a watch still registered a grace
    window past its deadline SIGABRTs the process — a dead rank a
    supervisor restarts beats a silent forever-hang holding the gang's
    preemption barrier."""

    def __init__(self):
        self._cv = threading.Condition(threading.Lock())
        self._watches: Dict[int, dict] = {}  # guarded-by: _cv
        self._ids = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self.timeout_s = 0.0
        #: "" or "abort" (FLAGS_watchdog_escalate)
        self.escalate = ""

    def set_timeout(self, secs: float) -> None:
        with self._cv:
            # the predicate the monitor loop wakes on changes UNDER the
            # lock (the concurrency lint's Condition contract): a notify
            # with no state change wakes waiters to an unchanged world
            self.timeout_s = float(secs)
            self._cv.notify()

    def _abort_grace(self) -> float:
        """How long past the deadline a fired-but-still-registered watch
        gets for the async raise to land before the SIGABRT tier."""
        return max(1.0, min(self.timeout_s, 10.0))

    def _fh_rearm_locked(self) -> None:
        """(Re)arm the process-wide faulthandler timer to the earliest
        un-fired deadline (cancel when none): unlike :func:`dump_state`,
        faulthandler dumps from its own C-level thread, so the stacks
        land even when a hung C call holds the GIL forever."""
        import faulthandler
        deadlines = [e["deadline"] for e in self._watches.values()
                     if not e["fired"]]
        try:
            if not deadlines:
                faulthandler.cancel_dump_traceback_later()
            else:
                faulthandler.dump_traceback_later(
                    max(min(deadlines) - time.monotonic(), 0.05),
                    exit=False)
        except Exception:     # faulthandler disabled/unavailable: the
            pass              # python-level dump path still works

    @contextlib.contextmanager
    def watch(self, site: str):
        t = self.timeout_s
        if t <= 0:
            yield
            return
        entry = {"tid": threading.get_ident(), "site": site,
                 "deadline": time.monotonic() + t, "timeout": t,
                 "abort_at": time.monotonic() + t + self._abort_grace(),
                 "fired": False, "dump": None}
        with self._cv:
            wid = next(self._ids)
            self._watches[wid] = entry
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="pt-watchdog")
                self._thread.start()
            self._fh_rearm_locked()
            self._cv.notify()
        delivered = False
        try:
            yield
        except HungStepError as he:
            delivered = True
            if entry["fired"]:
                # enrich the bare async-raised error with the diagnosis
                raise HungStepError(self._msg(entry)) from he
            raise
        finally:
            with self._cv:
                self._watches.pop(wid, None)
                self._fh_rearm_locked()
            if entry["fired"] and not delivered:
                # the watched call ended (returned, or raised its OWN
                # error) after the deadline fired but before the async
                # exception landed — withdraw it on EVERY exit path, or
                # the stale HungStepError detonates at some arbitrary
                # later bytecode in this thread, masking the real outcome
                # (best effort: delivery racing this cancel still raises
                # HungStepError, just possibly a frame later)
                _async_raise(entry["tid"], None)
        if entry["fired"]:
            raise HungStepError(self._msg(entry))

    @staticmethod
    def _msg(entry: dict) -> str:
        where = entry["dump"] or \
            "<dump still writing — check FLAGS_watchdog_dump_dir>"
        return (f"step hung: {entry['site']!r} exceeded "
                f"FLAGS_watchdog_timeout_s={entry['timeout']}s; thread "
                f"stacks + telemetry dumped to {where}")

    def _abort(self, entry: dict) -> None:
        """SIGABRT escalation: the async HungStepError never landed — the
        watched thread is stuck inside C.  Dump every stack through
        faulthandler (signal-safe, GIL-independent) and abort; the exit
        is the diagnosis a supervisor can act on."""
        import faulthandler
        sys.stderr.write(
            f"paddle_tpu watchdog: {entry['site']!r} still hung "
            f"{self._abort_grace():.1f}s past its "
            f"{entry['timeout']}s deadline (async raise never landed — "
            "C-level hang); FLAGS_watchdog_escalate=abort -> SIGABRT\n")
        sys.stderr.flush()
        try:
            faulthandler.dump_traceback(all_threads=True)
        except Exception:
            pass
        # if SIGABRT is blocked/handled the loop must not spin on this
        # entry forever
        entry["abort_at"] = float("inf")
        os.kill(os.getpid(), signal.SIGABRT)

    def _loop(self):
        while True:
            abort_entry = None
            with self._cv:
                now = time.monotonic()
                pending = [(w, e) for w, e in self._watches.items()
                           if not e["fired"]]
                expired = [(w, e) for w, e in pending
                           if e["deadline"] <= now]
                for _, e in expired:
                    e["fired"] = True
                if not expired:
                    fired = [e for e in self._watches.values()
                             if e["fired"]]
                    if self.escalate == "abort":
                        abort_entry = next(
                            (e for e in fired if e["abort_at"] <= now),
                            None)
                    if abort_entry is None:
                        deadlines = [e["deadline"] for _, e in pending]
                        if self.escalate == "abort":
                            deadlines += [e["abort_at"] for e in fired]
                        nxt = min(deadlines, default=now + 5.0)
                        self._cv.wait(timeout=max(nxt - now, 0.02))
                        continue
            if abort_entry is not None:
                self._abort(abort_entry)
                continue
            for wid, e in expired:    # I/O outside the lock
                try:
                    e["dump"] = dump_state(
                        f"watched section exceeded {e['timeout']}s",
                        site=e["site"])
                except Exception:
                    e["dump"] = "<dump failed>"
                _WATCHDOG_CTR.inc(1, site=e["site"])
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "watchdog.fired", "resilience",
                        {"site": e["site"], "dump": e["dump"]})
                with self._cv:
                    # only async-raise while the watch is still
                    # registered: if the "hung" call returned during the
                    # dump, the exiting watch() raises directly — an
                    # unconditional raise here could detonate at an
                    # arbitrary later bytecode in that thread
                    if wid in self._watches:
                        _async_raise(e["tid"], HungStepError)


WATCHDOG = Watchdog()


# ---------------------------------------------------------------------------
# background checkpoint daemon
# ---------------------------------------------------------------------------

def _report_capture_bytes(n: int) -> None:
    """Attribute the in-flight snapshot copies' device bytes to the HBM
    accountant's ``ckpt_capture`` class (paddle_tpu.hbm) — best-effort,
    a telemetry failure must never touch the checkpoint path."""
    try:
        from . import hbm as _hbm
        _hbm.set_ckpt_capture_bytes(n)
    except Exception:
        pass


class CheckpointDaemon:
    """Gang-aware background checkpointing off the training thread.

    Split of labor, chosen so the hot path never serializes:

    - **capture** (training thread, at a step boundary): each persistable
      gets a device-side ``jnp.copy`` — an async dispatch, no host sync.
      The copy is essential, not an optimization: the executor DONATES
      read-write persistables to the next step, so a bare reference
      captured now is exactly the buffer step *n+1* deletes.
    - **serialize + commit** (daemon thread): materialize the copies
      (device→host sync lands HERE), hand them to orbax's async writer,
      drain it, fsync the checkpoint root, and only then count the step
      as committed and announce it to the gang (``GangRendezvous``) —
      the rank-0 leader publishes the ``COMMITTED`` manifest once every
      rank holds the step.

    Cadence comes from ``FLAGS_checkpoint_interval_steps`` and/or
    ``FLAGS_checkpoint_interval_secs`` (constructor args override; the
    seconds trigger is still evaluated at step boundaries — a mid-step
    snapshot would capture half-updated state).  Only the LATEST pending
    snapshot is kept when the writer falls behind: checkpoints are a
    recovery floor, not a log.  Two tuning knobs ride along:
    ``FLAGS_checkpoint_cadence_stretch_frac`` adapts the cadence to the
    observed save latency (a save slower than that fraction of the
    interval stretches the effective interval, bumping
    ``paddle_tpu_checkpoint_cadence_stretched_total``), and
    ``FLAGS_checkpoint_capture_chunk_mb`` bounds the capture window's
    extra HBM by materializing the snapshot in chunks (see
    :meth:`capture`).

    Wiring options::

        daemon = CheckpointDaemon(ckpt, interval_steps=100).start()
        with PreemptionGuard(ckpt, executor=exe, daemon=daemon) as g:
            for step in range(start, total):
                exe.run(...)
                g.completed_step(step + 1)   # forwards to the daemon
        # guard exit: the emergency save degrades to "commit the
        # in-flight async save" instead of a synchronous full write

    or, for loops that do not track step indices,
    ``daemon.attach(exe)`` drives it from the executor's step-boundary
    hook (the daemon then counts completed runs itself — attach AFTER
    the startup program so step 0 is the first training step).
    """

    def __init__(self, checkpoint, program=None, scope=None,
                 interval_steps: Optional[int] = None,
                 interval_secs: Optional[float] = None,
                 gang=None, capture_chunk_mb: Optional[int] = None,
                 cadence_stretch_frac: Optional[float] = None):
        from .flags import get_flags
        fl = get_flags(["FLAGS_checkpoint_interval_steps",
                        "FLAGS_checkpoint_interval_secs",
                        "FLAGS_checkpoint_capture_chunk_mb",
                        "FLAGS_checkpoint_cadence_stretch_frac"])
        self.checkpoint = checkpoint
        self.program = program
        self.scope = scope
        self.interval_steps = (
            int(fl["FLAGS_checkpoint_interval_steps"])
            if interval_steps is None else int(interval_steps))
        self.interval_secs = (
            float(fl["FLAGS_checkpoint_interval_secs"])
            if interval_secs is None else float(interval_secs))
        self.capture_chunk_mb = (
            int(fl["FLAGS_checkpoint_capture_chunk_mb"])
            if capture_chunk_mb is None else int(capture_chunk_mb))
        self.cadence_stretch_frac = (
            float(fl["FLAGS_checkpoint_cadence_stretch_frac"])
            if cadence_stretch_frac is None
            else float(cadence_stretch_frac))
        if gang is None:
            try:
                from .distributed.env import GangRendezvous
                gang = GangRendezvous.from_env()
            except ConnectionError:
                # PADDLE_GANG_COORD exported but unreachable: raising is
                # the contract (a silent gang-less rank splits the
                # coordination plane — see from_env)
                raise
            except Exception:
                gang = None
        self.gang = gang
        self._mu = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._pending: Optional[tuple] = \
            None  # guarded-by: _mu  ((step, state, kind))
        # phase alignment: a FRESH daemon on a respawned rank must
        # continue the gang's ORIGINAL step cadence, not restart it at
        # the resume step — a zero anchor would make its first capture
        # land at resume+1 (then resume+1+interval, ...) while its peers
        # keep capturing at interval multiples, so committed step sets
        # drift uneven across ranks and commit_latest's intersection
        # stops advancing.  Anchor to the restored checkpoint step (the
        # gang-manifest step after _resume_gang's prune), which is
        # exactly the step every peer last captured.  Cold starts see no
        # checkpoint -> anchor 0, the pre-PR-7 behavior.  Corollary: a
        # run REUSING a non-empty checkpoint dir without resuming from
        # it inherits the stale anchor — but that configuration never
        # worked (orbax refuses saves at indices <= its latest step, so
        # low-step captures were silently dropped before too); start
        # fresh dirs fresh, or resume via resume_or_init.
        anchor = 0
        try:
            if checkpoint is not None and \
                    hasattr(checkpoint, "latest_step"):
                anchor = int(checkpoint.latest_step() or 0)
        except Exception:
            anchor = 0
        self._last_capture_step = anchor
        self._last_capture_t = time.monotonic()
        self._last_committed: Optional[int] = None
        self._last_save_s = 0.0  # guarded-by: _mu  (daemon writes, due() reads)
        self._stretch_noted = False     # training thread only
        self._thread: Optional[threading.Thread] = None
        self._hooked: list = []
        # attach()-mode steps continue the global numbering from the
        # anchor too, so a respawned attach-driven rank stays on phase
        self._auto_step = anchor
        self.error: Optional[BaseException] = None

    # -- wiring --------------------------------------------------------------
    def start(self) -> "CheckpointDaemon":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="pt-ckpt-daemon")
            self._thread.start()
        return self

    def attach(self, executor) -> "CheckpointDaemon":
        """Drive the cadence from ``executor``'s step-boundary hook: every
        completed ``run()`` counts as one step."""
        executor.add_step_hook(self._executor_hook)
        self._hooked.append(executor)
        return self

    def detach(self) -> None:
        for exe in self._hooked:
            exe.remove_step_hook(self._executor_hook)
        self._hooked.clear()

    def _executor_hook(self, executor, scope) -> None:
        self._auto_step += 1
        self.step_completed(self._auto_step, scope=scope)

    # -- training-thread side ------------------------------------------------
    def due(self, step: int) -> bool:
        base = bool(
            (self.interval_steps
             and step - self._last_capture_step >= self.interval_steps)
            or (self.interval_secs
                and time.monotonic() - self._last_capture_t
                >= self.interval_secs))
        if not base:
            return False
        # adaptive cadence: a writer slower than the configured interval
        # stretches the effective interval instead of queueing snapshots
        # the daemon will drop anyway — the last observed save must be at
        # most FLAGS_checkpoint_cadence_stretch_frac of the capture gap
        if self.cadence_stretch_frac > 0:
            with self._mu:
                last_save_s = self._last_save_s
            if last_save_s > 0:
                need = last_save_s / self.cadence_stretch_frac
                if time.monotonic() - self._last_capture_t < need:
                    if not self._stretch_noted:
                        self._stretch_noted = True
                        from . import checkpoint as _ckpt
                        _ckpt.STRETCH_CTR.inc()
                        if _monitor.TRACER.enabled:
                            _monitor.TRACER.instant(
                                "checkpoint.cadence_stretched",
                                "checkpoint",
                                {"step": int(step),
                                 "save_s": round(last_save_s, 3),
                                 "stretched_to_s": round(need, 3)})
                    return False
        return True

    def step_completed(self, step: int, scope=None) -> bool:
        """Step-boundary notification (training thread).  One int compare
        off-cadence; on-cadence it snapshots persistables as device-side
        copies and wakes the daemon.  Returns True iff a snapshot was
        taken.  Also re-raises a failure the daemon hit in the
        background — silent checkpoint loss is not an option."""
        self.check()
        step = int(step)
        if not self.due(step):
            return False
        return self.capture(step, scope=scope)

    @staticmethod
    def _quarantined(step: int, kind: str) -> bool:
        """Numerics quarantine gate: once the anomaly engine has the run
        poisoned (NaN/Inf in grads/weights), HOLD every capture — a
        snapshot of poisoned state advancing the (gang) manifest would
        destroy the exact recovery floor quarantine exists to protect.
        The engine is force-polled first: captures are rare, so the
        materializing poll is off the steady-state path, and it closes
        the race where the poisoning step's stats are still in flight
        when its own capture comes due."""
        try:
            from .analysis import numerics as _numerics
        except Exception:
            return False
        if _numerics.mode() == "off":
            return False
        try:
            _numerics.ENGINE.poll(force=True)
        except Exception:
            pass
        if not _numerics.is_poisoned():
            return False
        _numerics.QUARANTINE_CTR.inc()
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "checkpoint.quarantine_hold", "checkpoint",
                {"step": int(step), "kind": kind,
                 "poisoned_since": _numerics.poisoned_since()})
        return True

    def capture(self, step: int, scope=None, kind: str = "daemon") -> bool:
        """Snapshot every persistable at a (consistent) step boundary —
        device arrays via async on-device copies, host arrays via host
        copies.  Default mode keeps every copy device-side (no sync on
        this thread) at the cost of transiently doubling the model's
        HBM during the capture window; with
        ``FLAGS_checkpoint_capture_chunk_mb`` > 0, copies are taken in
        bounded-size groups and each group is materialized to host
        before the next is copied, so the extra HBM is capped at the
        chunk size (the per-chunk device→host sync lands here).
        Returns False when the numerics quarantine HELD the capture."""
        from .framework.core import default_main_program
        from .framework.scope import global_scope
        from .io import get_program_persistable_vars
        import jax
        import jax.numpy as jnp
        if self._quarantined(step, kind):
            return False
        t0 = time.perf_counter()
        program = self.program or default_main_program()
        scope = scope or self.scope or global_scope()
        chunk_bytes = int(self.capture_chunk_mb) << 20
        state: Dict[str, Any] = {}
        group: List[tuple] = []
        group_bytes = 0
        chunks = 0
        # transient capture bytes reported to the HBM accountant: the
        # capture-window live-bytes spike is attributed to ckpt_capture
        # instead of reading as a leak.  Unchunked captures hold the
        # whole snapshot device-side until _save materializes it (the
        # daemon thread clears the report); chunked captures hold at
        # most one chunk (cleared per flush).
        dev_bytes = 0

        def _flush_group():
            nonlocal group, group_bytes, chunks
            for name, arr in group:
                # materializing frees the device copy before the next
                # chunk is taken — THIS is what bounds the HBM doubling
                state[name] = np.asarray(arr)
            if group:
                chunks += 1
            group = []
            group_bytes = 0
            _report_capture_bytes(0)

        for v in get_program_persistable_vars(program):
            val = scope.find_var(v.name)
            if val is None:
                raise RuntimeError(
                    f"persistable var {v.name!r} has no value in the "
                    "scope; did you run the startup program before "
                    "enabling the checkpoint daemon?")
            if isinstance(val, jax.Array):
                # jnp.copy preserves sharding, so a GSPMD/ZeRO-1-sharded
                # var's capture copy costs each device only its SHARD —
                # attribute per-device bytes, or the ckpt_capture class
                # over-reports by the mesh size on sharded snapshots
                from . import hbm as _hbm
                nbytes = _hbm.per_device_nbytes(val)
                if not chunk_bytes:
                    state[v.name] = jnp.copy(val)
                    dev_bytes += nbytes
                    continue
                if group and group_bytes + nbytes > chunk_bytes:
                    _flush_group()
                group.append((v.name, jnp.copy(val)))
                group_bytes += nbytes
                _report_capture_bytes(group_bytes)
            else:
                state[v.name] = np.array(val, copy=True)
        _flush_group()
        if dev_bytes:
            _report_capture_bytes(dev_bytes)
        with self._mu:
            self._pending = (int(step), state, kind)
            self._last_capture_step = int(step)
            self._last_capture_t = time.monotonic()
        self._stretch_noted = False
        if _monitor.TRACER.enabled:
            args = {"step": int(step), "kind": kind}
            if chunk_bytes:
                args["chunks"] = chunks
                args["chunk_mb"] = int(self.capture_chunk_mb)
            _monitor.TRACER.add_complete(
                "checkpoint.capture", "checkpoint", t0,
                time.perf_counter(), args)
        self._wake.set()
        return True

    # -- daemon-thread side --------------------------------------------------
    def _loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            while True:
                with self._mu:
                    pending, self._pending = self._pending, None
                if pending is None:
                    break
                try:
                    self._save(*pending)
                except BaseException as e:  # surfaced at the next
                    self.error = e          # step_completed()/stop()
            if self._stop.is_set():
                return

    def _save(self, step: int, state: Dict[str, Any], kind: str) -> None:
        # materialize the device-side copies: THIS is where the
        # device→host sync lands, a thread the training loop never waits
        # on (already host arrays in chunked-capture mode).
        # checkpoint.save_arrays then rides orbax's async writer
        # (plus the checkpoint.write retry/injection plane).
        t_save0 = time.monotonic()
        host = {name: np.asarray(v) for name, v in state.items()}
        # the device-side snapshot copies are gone now — clear the
        # accountant's ckpt_capture attribution (unchunked captures
        # reported the whole snapshot at capture time)
        _report_capture_bytes(0)
        if not self.checkpoint.save_arrays(step, host, force=True,
                                           kind=kind):
            return
        # durable commit before announcing: the gang protocol's whole
        # point is that an announced step survives a SIGKILL
        if hasattr(self.checkpoint, "commit"):
            self.checkpoint.commit(kind="rank")
        elif hasattr(self.checkpoint, "wait_until_finished"):
            self.checkpoint.wait_until_finished()
        with self._mu:
            self._last_committed = int(step)
            # observed end-to-end save time (materialize + write +
            # durable commit) feeds the adaptive cadence in due()
            self._last_save_s = time.monotonic() - t_save0
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "checkpoint.committed", "checkpoint",
                {"step": int(step), "kind": kind})
        self._announce(step)

    def _announce(self, step: int) -> None:
        gang = self.gang
        if gang is None:
            return
        steps = [int(step)]
        if hasattr(self.checkpoint, "all_steps"):
            steps = self.checkpoint.all_steps()
        gang.announce(step, steps=steps)
        if gang.is_leader:
            from . import checkpoint as _ckpt
            published = gang.commit_latest()
            if published is not None:
                _ckpt.COMMIT_CTR.inc(1, kind="gang")
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "checkpoint.gang_commit", "checkpoint",
                        {"step": int(published)})

    # -- teardown ------------------------------------------------------------
    @property
    def last_committed(self) -> Optional[int]:
        with self._mu:
            return self._last_committed

    def wait_committed(self, step: int, timeout_s: float = 60.0,
                       poll_s: float = 0.005) -> bool:
        """Block until ``step`` is the daemon's durably committed step (a
        synchronous commit point for callers that need one — tests, or a
        loop about to externalize state).  Re-raises a background save
        failure; returns False on timeout."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            self.check()
            if self.last_committed == int(step):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def check(self) -> None:
        """Re-raise a background save failure on the caller."""
        if self.error is not None:
            e, self.error = self.error, None
            raise RuntimeError(
                "checkpoint daemon failed in the background") from e

    def stop(self, final_step: Optional[int] = None,
             scope=None) -> Optional[int]:
        """Stop the daemon; with ``final_step``, run the emergency
        protocol: if that step is already committed or its snapshot is
        already in flight, this just COMMITS the in-flight async save —
        the preemption-deadline win over a full synchronous write.
        Otherwise the state is captured now (we are on the exit path; the
        capture itself is still just device copies) and the daemon thread
        flushes it.  Returns the last durably committed step."""
        if final_step is not None:
            final_step = int(final_step)
            with self._mu:
                pending_step = (self._pending[0]
                                if self._pending is not None else None)
                committed = self._last_committed
            if committed != final_step and pending_step != final_step:
                self.capture(final_step, scope=scope, kind="emergency")
        self._stop.set()
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join()
        else:
            # never started (or already stopped): drain inline
            while True:
                with self._mu:
                    pending, self._pending = self._pending, None
                if pending is None:
                    break
                try:
                    self._save(*pending)
                except BaseException as e:
                    self.error = e
        self.detach()
        self.check()
        return self.last_committed


# ---------------------------------------------------------------------------
# preemption guard + resume
# ---------------------------------------------------------------------------

class PreemptionGuard:
    """Graceful SIGTERM/SIGINT drain for a training loop.

    ::

        ckpt = CheckpointManager(ckpt_dir)
        start = resume_or_init(ckpt, exe, startup_program=startup,
                               main_program=main)
        with PreemptionGuard(ckpt, executor=exe, program=main) as guard:
            for step in range(start, total_steps):
                exe.run(main, feed=batch(step), fetch_list=[loss])
                guard.completed_step(step + 1)
                if guard.preempted:
                    break
        # guard exit (preempted): drain in-flight steps, force an
        # emergency checkpoint at the last complete step, export
        # telemetry, SystemExit(exit_code)

    The signal handler only sets a flag — checkpointing from inside a
    handler could snapshot a half-dispatched step.  The loop polls
    ``guard.preempted`` at step boundaries (where the scope is a complete,
    consistent state) and breaks; everything irreversible happens on the
    normal exit path.  Handlers are restored on exit.  Signal installation
    requires the main thread; elsewhere the guard still works via
    :meth:`trigger` (and warns once).
    """

    def __init__(self, checkpoint=None, executor=None, program=None,
                 scope=None, signals=(signal.SIGTERM, signal.SIGINT),
                 export_dir: Optional[str] = None,
                 exit_on_preempt: bool = True, exit_code: int = 0,
                 daemon: Optional["CheckpointDaemon"] = None,
                 gang=None):
        self.checkpoint = checkpoint
        self.executor = executor
        self.program = program
        self.scope = scope
        self.signals = tuple(signals)
        self.export_dir = export_dir
        self.exit_on_preempt = exit_on_preempt
        self.exit_code = exit_code
        # background daemon: completed_step() feeds its cadence, and the
        # emergency save degrades to committing its in-flight async write
        self.daemon = daemon
        if daemon is not None and checkpoint is None:
            self.checkpoint = daemon.checkpoint
        if gang is None and daemon is not None:
            gang = daemon.gang
        if gang is None:
            try:
                from .distributed.env import GangRendezvous
                gang = GangRendezvous.from_env()
            except ConnectionError:
                # PADDLE_GANG_COORD exported but unreachable: raising is
                # the contract (a silent gang-less rank splits the
                # coordination plane — see from_env)
                raise
            except Exception:
                gang = None
        self.gang = gang
        self._preempted = threading.Event()
        self._signum = signal.SIGTERM
        self._noted = False
        self._last_step: Optional[int] = None
        self._old: Dict[int, Any] = {}

    # -- signal plumbing -----------------------------------------------------
    def _handler(self, signum, frame):
        self.trigger(signum)

    def trigger(self, signum: int = signal.SIGTERM) -> None:
        """Record a preemption request (the signal handler body; callable
        directly from tests or cluster-notification hooks).

        LOCK-FREE on purpose: this runs on the main thread *interrupting
        its own frame*, which may be inside a tracer/metric critical
        section — taking any of those non-reentrant locks here would
        self-deadlock the process at the exact moment it must drain.
        Event.set() alone is safe; the counter/tracer bumps happen later,
        on the drain/exit path (:meth:`_note_signal`)."""
        self._signum = signum
        self._preempted.set()

    def _note_signal(self) -> None:
        """Deferred observability for the signal: runs on the normal exit
        path, where taking the metric/tracer locks is safe."""
        if self._noted or not self._preempted.is_set():
            return
        self._noted = True
        signum = self._signum
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        _PREEMPT_CTR.inc(1, signal=name)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant("preemption.signal", "resilience",
                                    {"signal": int(signum)})

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    def completed_step(self, step: int) -> None:
        """Mark ``step`` steps as fully complete (scope state consistent
        through that step) — the emergency checkpoint saves at this
        index, and an attached :class:`CheckpointDaemon` gets its
        step-boundary notification."""
        self._last_step = int(step)
        if self.daemon is not None:
            self.daemon.step_completed(step, scope=self.scope)

    # -- drain + emergency checkpoint ---------------------------------------
    def drain(self) -> None:
        """Block until every in-flight dispatched step has retired (the
        executor's throttle queue) — after this the scope holds fully
        computed values."""
        if self.executor is not None and hasattr(self.executor, "drain"):
            with _monitor.TRACER.span("preemption.drain", "resilience"):
                self.executor.drain()

    def emergency_checkpoint(self) -> Optional[int]:
        """Drain, then make the last complete step durable; returns the
        step saved (None when no checkpoint manager / no completed step).

        With a :class:`CheckpointDaemon` attached this degrades to
        "commit the in-flight async save" — under a preemption deadline
        the synchronous cost is a drain, not a full serialize+write.
        Either way the step is fsync-durable before the gang announce:
        a rank must never advertise a checkpoint a crash could lose."""
        self.drain()
        if self._last_step is None or \
                (self.checkpoint is None and self.daemon is None):
            return None
        step = self._last_step
        durable = None
        with _monitor.TRACER.span("preemption.checkpoint", "resilience",
                                  step=step):
            if self.daemon is not None:
                durable = self.daemon.stop(final_step=step,
                                           scope=self.scope)
            else:
                try:
                    self.checkpoint.save(step, program=self.program,
                                         scope=self.scope, force=True,
                                         kind="emergency")
                except TypeError:   # foreign manager without kind=
                    self.checkpoint.save(step, program=self.program,
                                         scope=self.scope, force=True)
                # the save may be async (orbax): the process is about to
                # exit, so it must land on disk NOW
                if hasattr(self.checkpoint, "commit"):
                    durable = self.checkpoint.commit(kind="rank")
                else:
                    wait = getattr(self.checkpoint, "_mgr", None)
                    if wait is not None and \
                            hasattr(wait, "wait_until_finished"):
                        wait.wait_until_finished()
                    if hasattr(self.checkpoint, "latest_step"):
                        durable = self.checkpoint.latest_step()
                    else:
                        durable = step      # no way to ask; trust it
        if durable == step:
            self._gang_commit(step)
        else:
            # never advertise a step that is not actually on disk (an
            # orbax write can be silently refused when a stale NEWER
            # step lingers): a unanimous-but-wrong announce would let
            # the leader publish a manifest no rank can restore
            import warnings
            warnings.warn(
                f"emergency checkpoint at step {step} is not the durable "
                f"latest ({durable}); skipping the gang announce — the "
                "manifest stays at the last committed step")
        return step

    def _gang_commit(self, step: int) -> None:
        """Gang barrier for the emergency save: announce this rank's
        durable step; the rank-0 leader publishes ``COMMITTED <step>``
        only when EVERY rank announced the same step within
        ``FLAGS_gang_commit_timeout_s`` — otherwise the manifest stays at
        the last step the whole gang agreed on, and ``resume_or_init``
        refuses the torn newer saves."""
        if self.gang is None:
            return
        from .flags import get_flags
        timeout = float(get_flags("FLAGS_gang_commit_timeout_s")
                        ["FLAGS_gang_commit_timeout_s"])
        ckpt = self.checkpoint
        steps = ckpt.all_steps() if hasattr(ckpt, "all_steps") else [step]
        try:
            self.gang.announce(step, steps=steps)
            if not self.gang.is_leader:
                return
            from . import checkpoint as _ckpt
            with _monitor.TRACER.span("checkpoint.gang_barrier",
                                      "checkpoint", step=int(step)):
                ok = self.gang.wait_commit(step, timeout)
            if ok:
                _ckpt.COMMIT_CTR.inc(1, kind="gang")
            else:
                import warnings
                warnings.warn(
                    f"gang commit of emergency step {step} timed out "
                    f"after {timeout}s (a rank died or saved a different "
                    "step); the manifest stays at "
                    f"{self.gang.committed_step()} and the torn save "
                    "will be refused at resume")
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "checkpoint.gang_commit_timeout", "checkpoint",
                        {"step": int(step)})
        except Exception:
            import warnings
            warnings.warn("gang rendezvous failed during the emergency "
                          "drain; exiting with the rank-local checkpoint")

    # -- context manager -----------------------------------------------------
    def __enter__(self):
        for s in self.signals:
            try:
                self._old[s] = signal.signal(s, self._handler)
            except ValueError:      # not the main thread
                import warnings
                warnings.warn(
                    "PreemptionGuard: cannot install signal handlers "
                    "outside the main thread; use guard.trigger()")
                break
        return self

    def __exit__(self, et, ev, tb):
        try:
            # the emergency path runs with OUR handlers still installed:
            # a scheduler's follow-up SIGTERM (or a second Ctrl-C) during
            # the drain/save just re-sets the already-set flag instead of
            # killing the process mid-emergency-checkpoint
            if et is None and self.preempted:
                self.emergency_checkpoint()
                if self.export_dir:
                    try:
                        _monitor.export(self.export_dir)
                    except Exception:   # telemetry must not block the exit
                        pass
        finally:
            for s, old in self._old.items():
                try:
                    signal.signal(s, old)
                except ValueError:
                    pass
            self._old.clear()
            self._note_signal()
        if et is None and hasattr(self.gang, "goodbye"):
            # socket gang: a CLEAN exit of the guarded block (finished,
            # or preemption fully drained) is an orderly DEPARTURE —
            # without it the rank's silence reads as a death and parks
            # every peer at the rejoin barrier for a respawn that never
            # comes.  An exception propagating through the guard
            # deliberately does NOT say goodbye: a crashed rank IS dead
            # (the launcher respawns it; survivors should drain).
            self.gang.goodbye()
        if et is None and self.preempted and self.exit_on_preempt:
            raise SystemExit(self.exit_code)
        return False


def resume_or_init(checkpoint, executor, startup_program=None,
                   main_program=None, scope=None, gang=None) -> int:
    """Restart a training loop from the last complete checkpoint.

    Runs the startup program (vars must exist before a restore can fill
    them — and a cold start needs its initializers anyway), then restores
    the latest checkpoint when one exists.  Returns the number of COMPLETE
    steps — the loop resumes at that index, so an interrupted run's loss
    trajectory continues exactly where the emergency save left it::

        start = resume_or_init(ckpt, exe, startup_program=startup,
                               main_program=main)
        for step in range(start, total_steps):
            ...

    In a gang (``gang`` passed, or launched with ``PADDLE_GANG_DIR`` and
    >1 ranks) the unit of recovery is the GANG, not the rank: only the
    step named by the leader's ``COMMITTED`` manifest is restorable.  A
    rank-local checkpoint newer than the manifest is a torn save (some
    other rank never finished it) — it is pruned and the gang-committed
    step restored instead; with no manifest at all, every checkpoint is
    refused and the run cold-starts.  Each refusal bumps
    ``paddle_tpu_checkpoint_torn_rejects_total``.
    """
    from .framework.core import default_startup_program
    if gang is None:
        try:
            from .distributed.env import GangRendezvous
            gang = GangRendezvous.from_env()
        except ConnectionError:
            raise
        except Exception:
            gang = None
    startup = startup_program or default_startup_program()
    executor.run(startup, scope=scope)
    if gang is not None:
        return _resume_gang(checkpoint, gang, main_program, scope)
    step = checkpoint.latest_step()
    if step is None:
        return 0
    checkpoint.restore(step, program=main_program, scope=scope)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant("preemption.resume", "resilience",
                                {"step": int(step)})
    return int(step)


def _resume_gang(checkpoint, gang, main_program, scope) -> int:
    """Gang-manifest resume: restore exactly the committed step, refuse
    (and prune) anything newer — see :func:`resume_or_init`."""
    import warnings
    from . import checkpoint as _ckpt
    committed = gang.committed_step()
    latest = checkpoint.latest_step()
    if committed is None:
        if latest is not None:
            _ckpt.TORN_CTR.inc()
            if _monitor.TRACER.enabled:
                _monitor.TRACER.instant(
                    "checkpoint.torn_reject", "checkpoint",
                    {"latest": int(latest), "committed": None})
            warnings.warn(
                f"rank {gang.rank}: refusing checkpoint step {latest} — "
                "no gang COMMITTED manifest exists (the save tore before "
                "every rank finished); cold-starting")
            if hasattr(checkpoint, "prune_after"):
                # the refused steps must also GO: orbax silently rejects
                # saves at indices ≤ its latest step, so leaving them
                # would suppress the cold-started run's checkpoints (and
                # a later emergency could even gang-commit the previous
                # run's stale weights)
                checkpoint.prune_after(-1)
        return 0
    if latest is not None and latest != committed:
        _ckpt.TORN_CTR.inc()
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "checkpoint.torn_reject", "checkpoint",
                {"latest": int(latest), "committed": int(committed)})
        warnings.warn(
            f"rank {gang.rank}: checkpoint step {latest} is not the "
            f"gang-committed step {committed} (torn multi-rank save); "
            "restoring the committed step")
    if hasattr(checkpoint, "prune_after"):
        # torn steps past the manifest must go: orbax refuses saves at
        # indices ≤ its latest step, so a resumed run could otherwise
        # never checkpoint again until it re-passed the torn step
        checkpoint.prune_after(committed)
    try:
        # re-announce the POST-prune holdings: the rank's pre-death
        # announcement may still list the just-pruned steps, and a
        # leader intersecting against it could commit a manifest step
        # this rank no longer has on disk
        steps = checkpoint.all_steps() \
            if hasattr(checkpoint, "all_steps") else [committed]
        gang.announce(committed, steps=steps or [committed])
    except Exception:
        warnings.warn("gang re-announce after torn-step prune failed; "
                      "the next daemon commit will refresh it")
    checkpoint.restore(committed, program=main_program, scope=scope)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant("preemption.resume", "resilience",
                                {"step": int(committed), "gang": True})
    return int(committed)


# ---------------------------------------------------------------------------
# flag sync (mirrors monitor._sync_from_flags: whichever of the two
# modules imports second sees the other's already-bootstrapped values)
# ---------------------------------------------------------------------------

def _sync_from_flags():
    try:
        from .flags import get_flags
        fl = get_flags(["FLAGS_fault_inject", "FLAGS_watchdog_timeout_s",
                        "FLAGS_watchdog_escalate"])
    except Exception:           # flags mid-bootstrap: side effects re-sync
        return
    if fl["FLAGS_fault_inject"]:
        configure(str(fl["FLAGS_fault_inject"]))
    WATCHDOG.set_timeout(float(fl["FLAGS_watchdog_timeout_s"]))
    WATCHDOG.escalate = str(fl["FLAGS_watchdog_escalate"])


_sync_from_flags()
