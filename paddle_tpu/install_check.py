"""Installation self-check (ref ``python/paddle/fluid/install_check.py``
run_check): trains a tiny linear model end-to-end on the active backend and
reports success."""

from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework import Executor
    from paddle_tpu.framework.core import Program, program_guard
    from paddle_tpu.framework.scope import Scope, scope_guard

    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("inp", shape=[2], dtype="float32")
        y = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(
            y, layers.assign(np.zeros((1, 1), np.float32))))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = Executor()
        exe.run(fluid.default_startup_program())
        out = None
        for _ in range(3):
            out, = exe.run(feed={"inp": np.ones((4, 2), np.float32)},
                           fetch_list=[loss])
        import jax
        print(f"Your paddle_tpu works well on {jax.default_backend()} "
              f"({len(jax.devices())} device(s)).")
        print("Your paddle_tpu is installed successfully!")
        return float(np.asarray(out))
