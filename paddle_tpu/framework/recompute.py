"""Activation rematerialization as a program transform (TPU-native; the
2019 reference stores every forward activation — SURVEY §5.7 notes its only
memory levers were eager deletion and reuse passes.  Modern large-model
training on TPU needs recompute to fit, so it is first-class here).

``apply_recompute(program, checkpoints)`` rewrites a program AFTER
``append_backward``:

1. the forward ops between consecutive checkpoint vars form segments;
2. each segment is re-emitted after the loss-grad seed with every
   intermediate renamed ``v@RECOMPUTE``, reading segment inputs through an
   ``optimization_barrier`` (the CSE fence — without it XLA merges the
   recomputation back into the stored original and no memory is saved);
3. backward ops are rewired to consume the ``@RECOMPUTE`` values.

Under XLA's liveness this makes segment intermediates die at the end of the
forward pass and re-materialize during backward — the effect of
``jax.checkpoint``, expressed in the Program IR.

RNG-stateful ops are NOT recomputed UNLESS their draw is replay-safe:
tagged dropout (a nonzero ``seed`` attr) derives its bits purely from
(per-step key, tag), so re-evaluating it reproduces the identical mask and
it recomputes like any pure op.  Counter-stream RNG ops (untagged dropout,
random_crop, …) would re-draw differently, so their outputs stay stored
and feed the recomputed chain through barriers.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from . import registry
from .core import Operator, Program

RECOMPUTE_SUFFIX = "@RECOMPUTE"
BARRIER_SUFFIX = "@RBAR"


def _is_rng_op(op: Operator) -> bool:
    if op.type == "dropout" and op.attrs.get("seed", 0):
        return False     # tagged dropout replays bit-identically — pure
    info = registry._REGISTRY.get(op.type)
    return bool(info and info.stateful_rng)


def apply_recompute(program: Program,
                    checkpoints: Sequence[str]) -> Program:
    """Rewrite IN PLACE; returns the program.  ``checkpoints`` are forward
    var names (segment boundaries) that stay stored."""
    block = program.global_block()
    ckpt = set(checkpoints)
    loss_seed = None
    for i, op in enumerate(block.ops):
        if op.type == "fill_constant" and any(
                n.endswith("@GRAD") for n in op.output_arg_names()):
            loss_seed = i
            break
    if loss_seed is None:
        raise ValueError("apply_recompute needs a program with backward "
                         "ops (call minimize()/append_backward first)")

    fwd_ops = block.ops[:loss_seed]
    bwd_ops = block.ops[loss_seed:]

    # vars the backward actually reads from the forward
    bwd_reads = set()
    for op in bwd_ops:
        bwd_reads.update(op.input_arg_names())

    # choose ops to recompute: forward ops after the FIRST checkpoint,
    # excluding RNG ops (their outputs stay stored — re-drawing a dropout
    # mask would silently change gradients)
    rename: Dict[str, str] = {}
    recompute_ops: List[Operator] = []
    barriered: Dict[str, str] = {}

    def barrier_name(v):
        # parameters/persistables can't be CSE'd with anything (they're
        # jit arguments) — fencing them is pure graph bloat
        var = block.vars.get(v)
        if var is not None and var.persistable:
            return v
        if v not in barriered:
            barriered[v] = v + BARRIER_SUFFIX
        return barriered[v]

    seen_ckpt = False
    for op in fwd_ops:
        outs = op.output_arg_names()
        if not seen_ckpt:
            if ckpt & set(outs):
                seen_ckpt = True
            continue
        if _is_rng_op(op) or op.type in ("feed",):
            continue
        needed = any(o in bwd_reads and o not in ckpt for o in outs)
        feeds_chain = any(o in rename for o in op.input_arg_names())
        if not needed and not feeds_chain:
            continue
        # clone with renamed inputs/outputs; every stored value entering
        # the chain passes through a CSE fence
        clone = Operator(block, op.type, attrs=dict(op.attrs))
        clone.inputs = {
            slot: [rename.get(n, barrier_name(n) if n else n)
                   for n in names]
            for slot, names in op.inputs.items()}
        clone.outputs = {}
        for slot, names in op.outputs.items():
            new = []
            for n in names:
                if not n:
                    new.append(n)
                elif n in ckpt:
                    # checkpoints stay stored: the clone's copy is a dead
                    # value XLA removes; chain reads hit the barrier'd
                    # original (the segment boundary)
                    new.append(n + RECOMPUTE_SUFFIX + "@DEAD")
                else:
                    rename[n] = n + RECOMPUTE_SUFFIX
                    new.append(rename[n])
            clone.outputs[slot] = new
        recompute_ops.append(clone)

    if not recompute_ops:
        return program

    # materialize barrier ops + vars
    barrier_ops: List[Operator] = []
    for src, dst in barriered.items():
        if not block.has_var(dst):
            v = block.var(src) if block.has_var(src) else None
            block.create_var(name=dst, shape=v.shape if v else None,
                             dtype=v.dtype if v else "float32")
        b = Operator(block, "optimization_barrier",
                     inputs={"X": [src]}, outputs={"Out": [dst]})
        barrier_ops.append(b)
    for clone in recompute_ops:
        for names in clone.outputs.values():
            for dst in names:
                if dst and not block.has_var(dst):
                    src = dst.split(RECOMPUTE_SUFFIX)[0]
                    v = block.var(src) if block.has_var(src) else None
                    block.create_var(name=dst,
                                     shape=v.shape if v else None,
                                     dtype=v.dtype if v else "float32")

    # rewire backward reads onto the recomputed values
    for op in bwd_ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]

    # op-list position is cosmetic — XLA schedules by dataflow and sinks
    # each recomputed chain next to the grads consuming it
    block.ops = fwd_ops + [bwd_ops[0]] + barrier_ops + \
        recompute_ops + bwd_ops[1:]
    program._bump_version()
    return program
