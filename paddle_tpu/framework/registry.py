"""Op registry: per-op-type JAX lowering + shape inference + grad synthesis.

TPU-native replacement for the reference's static kernel registration
(``paddle/fluid/framework/op_registry.h:199-243``, ``op_info.h``,
``grad_op_desc_maker.h``).  Where the reference registers per-device
C++/CUDA kernels keyed by ``OpKernelType``, we register a single *lowering*
function per op type that emits JAX ops while the surrounding Block is traced
into one XLA computation.  Shape inference (ref ``shape_inference.h``) is the
lowering itself run abstractly via ``jax.eval_shape`` — one source of truth.

Gradients: every op gets a synthesized ``<type>_grad`` op desc
(ref ``GradOpDescMakerBase``) whose lowering computes input grads with
``jax.vjp`` of the forward lowering.  Ops can override with a hand-written
grad maker where a cheaper formula exists (e.g. dropout reusing its saved
mask, softmax_with_cross_entropy).  XLA CSE merges the vjp's recomputed
forward with the original forward ops, so the generic path costs nothing
after compilation.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import Block, Operator, Variable, grad_var_name


class OpInfo:
    def __init__(self, type: str, lower: Callable, infer: Optional[Callable],
                 grad_maker: Optional[Callable], no_grad: bool,
                 stateful_rng: bool, raw: bool = False):
        self.type = type
        self.lower = lower
        self.infer = infer
        self.grad_maker = grad_maker    # None -> generic vjp grad
        self.no_grad = no_grad
        self.stateful_rng = stateful_rng
        # raw ops get (ctx, block, op, state) — needed by control flow which
        # must trace sub-blocks (ref while_op.cc executing a sub-block)
        self.raw = raw


_REGISTRY: Dict[str, OpInfo] = {}


def register_op(type: str, lower: Callable = None, *, infer: Callable = None,
                grad_maker: Callable = None, no_grad: bool = False,
                stateful_rng: bool = False, raw: bool = False):
    """Register an op lowering.  Usable as decorator or call.

    lower(ctx, ins, attrs) -> outs, where ins/outs are {slot: [jax arrays]}.
    Raw ops instead get lower(ctx, block, op, state).
    """
    def deco(fn):
        _REGISTRY[type] = OpInfo(type, fn, infer, grad_maker, no_grad,
                                 stateful_rng, raw)
        return fn
    if lower is not None:
        return deco(lower)
    return deco


def get_op_info(type: str) -> OpInfo:
    if type not in _REGISTRY:
        raise NotImplementedError(f"op {type!r} has no registered lowering")
    return _REGISTRY[type]


def has_op(type: str) -> bool:
    return type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# build-time shape/dtype inference (ref framework/operator.cc:913 InferShape)
# ---------------------------------------------------------------------------

_NO_INFER = {"feed", "fetch", "while", "conditional_block"}


class _AbstractCtx:
    """LowerCtx stand-in for abstract evaluation."""
    is_abstract = True

    def rng(self):
        return jax.random.key(0)

    def rng_tagged(self, tag):
        return jax.random.key(0)

    @property
    def mesh(self):
        return None


def infer_op(op: Operator, block: Block) -> None:
    """Populate output Variable shape/dtype by abstractly running the lowering."""
    if op.type not in _REGISTRY:
        return
    info = _REGISTRY[op.type]
    if info.infer is not None:
        info.infer(op, block)
        return
    if op.type in _NO_INFER or info.raw:
        # raw (sub-block) ops can't go through eval_shape; they either carry
        # an explicit infer above or are skipped
        return
    # symbolic batch dim: -1 is replaced by a sentinel for abstract eval and
    # mapped back afterwards (the reference's InferShape threads -1 natively).
    # The sentinel is a large prime so an accidental collision with a real
    # layer dim is vanishingly unlikely; the reverse map only runs when some
    # input actually had a -1.
    SENTINEL = 9973
    had_symbolic = False
    try:
        structs = {}
        for slot, names in op.inputs.items():
            arrs = []
            for n in names:
                if not n:
                    arrs.append(None)
                    continue
                v = block.var(n)
                if v.shape is None:
                    return  # can't infer yet
                if -1 in v.shape:
                    had_symbolic = True
                shape = tuple(SENTINEL if d == -1 else d for d in v.shape)
                arrs.append(jax.ShapeDtypeStruct(shape, jnp.dtype(v.dtype)))
            structs[slot] = arrs

        def f(ins):
            return info.lower(_AbstractCtx(), ins, op.attrs)

        outs = jax.eval_shape(f, structs)
        for slot, names in op.outputs.items():
            shaped = outs.get(slot, [])
            for n, s in zip(names, shaped):
                if s is None:
                    continue
                v = block.var(n)
                v.shape = tuple(-1 if (had_symbolic and d == SENTINEL) else d
                                for d in s.shape)
                v.dtype = np.dtype(s.dtype).name
    except Exception:
        # inference is best-effort at build time; executor re-checks at lower
        # time with concrete shapes.
        pass


# ---------------------------------------------------------------------------
# generic vjp-based gradient (stands in for GradOpDescMaker per op)
# ---------------------------------------------------------------------------

GENERIC_GRAD_TYPE_SUFFIX = "_grad"


def make_grad_ops(op: Operator, block: Block,
                  no_grad_set: set) -> List[Dict[str, Any]]:
    """Produce grad op descs for ``op`` (ref core.get_grad_op_desc,
    pybind.cc:726 → backward.py:431).

    Returns a list of dicts {type, inputs, outputs, attrs}.  Grad var names
    follow the reference convention ``<name>@GRAD``.
    """
    info = get_op_info(op.type)
    if info.no_grad:
        return []
    if info.grad_maker is not None:
        return info.grad_maker(op, block, no_grad_set)

    # generic: one grad op consuming fwd inputs + output-grads,
    # producing input-grads via jax.vjp of the forward lowering.
    g_inputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        g_inputs["X$" + slot] = list(names)
    for slot, names in op.outputs.items():
        g_inputs["OG$" + slot] = [grad_var_name(n) for n in names]
    g_outputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        outs = []
        for n in names:
            v = block.var(n) if block.has_var(n) else None
            if n in no_grad_set or (v is not None and v.stop_gradient):
                outs.append("")          # empty = not needed (ref kEmptyVarName)
            else:
                outs.append(grad_var_name(n))
        g_outputs["IG$" + slot] = outs
    attrs = dict(op.attrs)
    attrs["__fwd_type__"] = op.type
    return [{"type": op.type + GENERIC_GRAD_TYPE_SUFFIX,
             "inputs": g_inputs, "outputs": g_outputs, "attrs": attrs,
             "__generic__": True}]


def generic_grad_lower(ctx, ins: Dict[str, List], attrs: Dict[str, Any]):
    """Lowering for synthesized ``*_grad`` ops: jax.vjp of forward lowering."""
    fwd_type = attrs["__fwd_type__"]
    info = get_op_info(fwd_type)
    fwd_attrs = {k: v for k, v in attrs.items() if k != "__fwd_type__"}

    in_slots = sorted(s[2:] for s in ins if s.startswith("X$"))
    og_slots = sorted(s[3:] for s in ins if s.startswith("OG$"))

    flat_in, spec = [], []
    for slot in in_slots:
        arrs = ins["X$" + slot]
        spec.append((slot, len(arrs)))
        flat_in.extend(arrs)

    def fwd(*flat):
        d, i = {}, 0
        for slot, n in spec:
            d[slot] = list(flat[i:i + n])
            i += n
        if getattr(ctx, "amp", False):
            # cast INSIDE the vjp so master-weight grads come back f32
            # while the recomputed forward hits the MXU in bf16
            from .. import amp as _amp
            d = _amp.cast_ins(fwd_type, d)
        outs = info.lower(ctx, d, fwd_attrs)
        flat_out = []
        for slot in og_slots:
            flat_out.extend(outs.get(slot, []))
        return tuple(flat_out)

    primals_out, vjp = jax.vjp(fwd, *flat_in)
    cotangents = []
    i = 0
    for slot in og_slots:
        n = len(ins["OG$" + slot])
        for j in range(n):
            og = ins["OG$" + slot][j]
            if og is None:   # unused output: zero cotangent
                og = jnp.zeros(primals_out[i + j].shape,
                               primals_out[i + j].dtype)
            cotangents.append(og.astype(primals_out[i + j].dtype))
        i += n
    in_grads = vjp(tuple(cotangents))

    outs, i = {}, 0
    for slot, n in spec:
        outs["IG$" + slot] = list(in_grads[i:i + n])
        i += n
    return outs
