"""Program IR: Program / Block / Operator / Variable.

TPU-native rebuild of the Fluid program model (reference:
``paddle/fluid/framework/framework.proto:24-187``, ``python/paddle/fluid/framework.py``
Program:2899 Block:1556 Operator:1107 Variable:383 Parameter:3718).

Design departure from the reference: the IR is *not* consumed by a per-op kernel
dispatcher.  A whole Block is lowered in one pass to a single JAX function and
jit-compiled by XLA (see ``paddle_tpu.framework.executor``) — the role the
nGraph subgraph engine played in the reference
(``paddle/fluid/operators/ngraph/ngraph_engine.cc:249-531``) is here the *only*
execution path, which is the idiomatic shape for a TPU framework: static shapes,
one traced computation, XLA fusion instead of hand-written kernels.
"""

from __future__ import annotations

import copy
import itertools
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import unique_name

# ---------------------------------------------------------------------------
# dtype handling.  The reference uses VarType::Type protobuf enums
# (framework.proto:91-124); we use numpy dtype strings canonically and accept
# numpy / jax dtypes / python types on input.
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bfloat16": "bfloat16",
    "int": "int32",
    "long": "int64",
    "bool": "bool",
    bool: "bool",
    int: "int32",
    float: "float32",
}


def convert_dtype(dtype) -> str:
    """Normalize a dtype spec to a canonical string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        d = _DTYPE_ALIASES.get(dtype, dtype)
    elif dtype in _DTYPE_ALIASES:
        d = _DTYPE_ALIASES[dtype]
    else:
        d = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    allowed = {
        "float16", "bfloat16", "float32", "float64",
        "int8", "uint8", "int16", "int32", "int64", "bool",
    }
    if d not in allowed:
        raise TypeError(f"unsupported dtype {dtype!r}")
    return d


class VarType:
    """Variable kinds (reference ``framework.proto:91-124`` VarType::Type)."""

    DENSE_TENSOR = "dense_tensor"     # ref: LOD_TENSOR
    SELECTED_ROWS = "selected_rows"   # sparse {rows, values} pairs (embeddings)
    TENSOR_ARRAY = "tensor_array"     # ref: LOD_TENSOR_ARRAY
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


class Variable:
    """A typed symbolic value in a Block.

    Mirrors ``python/paddle/fluid/framework.py:383`` (Variable): name, shape,
    dtype, persistable, stop_gradient.  ``lod_level`` from the reference is
    replaced by an optional ``segments`` marker: ragged sequences are carried as
    dense padded data plus an explicit length/segment-id companion var (SURVEY
    §5.7 — the TPU-native stand-in for LoD).
    """

    def __init__(self, block: "Block", name: str, shape=None, dtype=None,
                 type: str = VarType.DENSE_TENSOR, persistable: bool = False,
                 stop_gradient: bool = False, initializer=None,
                 is_parameter: bool = False, trainable: bool = True,
                 regularizer=None, need_clip: bool = True):
        self.block = block
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.initializer = initializer
        self.is_parameter = is_parameter
        self.trainable = trainable
        self.regularizer = regularizer
        self.need_clip = need_clip
        # companion var name holding sequence lengths (LoD replacement)
        self.seq_len_var: Optional[str] = None
        # GSPMD sharding annotation: tuple of mesh-axis names (or None) per
        # dim, e.g. (None, "mp") for a column-parallel weight.  This is the
        # TPU-native stand-in for the reference's per-var placement logic in
        # multi_devices_graph_pass (params were only ever replicated or
        # round-robin "Reduce"-sharded there).
        self.dist_spec = None

    # -- sugar mirroring the reference Variable's operator overloads ---------
    def _binary(self, other, op, reverse=False):
        from ..layers import math_ops
        return math_ops._elementwise_binary(self, other, op, reverse)

    def __add__(self, o): return self._binary(o, "elementwise_add")
    def __radd__(self, o): return self._binary(o, "elementwise_add", True)
    def __sub__(self, o): return self._binary(o, "elementwise_sub")
    def __rsub__(self, o): return self._binary(o, "elementwise_sub", True)
    def __mul__(self, o): return self._binary(o, "elementwise_mul")
    def __rmul__(self, o): return self._binary(o, "elementwise_mul", True)
    def __truediv__(self, o): return self._binary(o, "elementwise_div")
    def __rtruediv__(self, o): return self._binary(o, "elementwise_div", True)
    def __pow__(self, o): return self._binary(o, "elementwise_pow")
    def __neg__(self):
        from ..layers import math_ops
        return math_ops.scale(self, scale=-1.0)

    # comparisons build compare ops (==/!= are NOT overridden: Variables
    # must stay usable in python containers)
    def __lt__(self, o): return self._binary(o, "less_than")
    def __le__(self, o): return self._binary(o, "less_equal")
    def __gt__(self, o): return self._binary(o, "greater_than")
    def __ge__(self, o): return self._binary(o, "greater_equal")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers
        return tensor_layers.cast(self, dtype)

    def to_dict(self):
        return {
            "name": self.name, "shape": list(self.shape) if self.shape else None,
            "dtype": self.dtype, "type": self.type,
            "persistable": self.persistable, "stop_gradient": self.stop_gradient,
            "is_parameter": self.is_parameter, "trainable": self.trainable,
            # the feed marker (layers.data sets it post-construction) must
            # survive serialization: the verifier and the static memory
            # planner classify feeds by it (tools/analyze.py runs offline)
            "is_data": bool(getattr(self, "is_data", False)),
        }


# Parameter is a Variable that is persistable + trainable
# (reference framework.py:3718).
Parameter = Variable


class Operator:
    """One op invocation: type + named input/output var lists + attrs.

    Mirrors ``OpDesc`` (reference ``framework.proto:43-62``) and python
    ``Operator`` (framework.py:1107).  inputs/outputs are {slot: [var names]}.
    """

    def __init__(self, block: "Block", type: str,
                 inputs: Optional[Dict[str, Any]] = None,
                 outputs: Optional[Dict[str, Any]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, Any] = dict(attrs or {})
        for slot, vs in (inputs or {}).items():
            self.inputs[slot] = [v.name if isinstance(v, Variable) else v
                                 for v in _as_list(vs)]
        for slot, vs in (outputs or {}).items():
            self.outputs[slot] = [v.name if isinstance(v, Variable) else v
                                  for v in _as_list(vs)]
        # role tagging (ref op_proto_maker.h OpRole + framework.py _op_role):
        # append_backward/optimizers set the program's current role so
        # clone(for_test=True) can prune the training-only tail
        role = getattr(block.program, "_current_role", None) if block else None
        if role is not None and "op_role" not in self.attrs:
            self.attrs["op_role"] = role

    def input(self, slot) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot) -> List[str]:
        return self.outputs.get(slot, [])

    def input_arg_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_arg_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        return f"Op({self.type}: {self.inputs} -> {self.outputs})"

    def to_dict(self):
        def _attr(v):
            if isinstance(v, Block):
                return {"__block__": v.idx}
            if isinstance(v, np.ndarray):
                return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            return v
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs,
                "attrs": {k: _attr(v) for k, v in self.attrs.items()}}


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class Block:
    """A straight-line list of ops over a var table; nests via parent_idx.

    Mirrors ``BlockDesc`` (framework.proto:178-187) / python Block
    (framework.py:1556).  Sub-blocks are used by control-flow ops
    (while/cond) whose lowering maps them onto ``lax.while_loop``/``lax.cond``.
    """

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, name=None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("tmp")
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, initializer=None,
                         trainable=True, regularizer=None,
                         need_clip=True) -> Variable:
        # parameters always live in block 0 / global scope (ref framework.py:1769)
        gb = self.program.global_block()
        v = Variable(gb, name, shape=shape, dtype=dtype, persistable=True,
                     initializer=initializer, is_parameter=True,
                     trainable=trainable, regularizer=regularizer,
                     need_clip=need_clip)
        gb.vars[name] = v
        return v

    def var(self, name) -> Variable:
        """Find var in this block or ancestors (ref Block._var_recursive)."""
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name) -> bool:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return True
            b = b.parent
        return False

    def var_local(self, name) -> Optional[Variable]:
        return self.vars.get(name)

    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        # build-time shape/dtype inference keeps Variable metadata populated,
        # standing in for the reference's C++ InferShape pass
        # (framework/operator.cc:913).
        from . import registry
        registry.infer_op(op, self)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        from . import registry
        registry.infer_op(op, self)
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        from . import registry
        registry.infer_op(op, self)
        return op

    def remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def all_parameters(self) -> List[Variable]:
        return [v for v in self.vars.values() if v.is_parameter]

    def to_dict(self):
        return {"idx": self.idx, "parent_idx": self.parent_idx,
                "vars": {n: v.to_dict() for n, v in self.vars.items()},
                "ops": [op.to_dict() for op in self.ops]}


_program_ids = itertools.count()

# serialized-program format version (ref framework/version.h kCurProgramVersion
# — a program saved by a newer format refuses to load on an older framework)
PROGRAM_FORMAT_VERSION = 1


class Program:
    """A list of Blocks; block 0 is global (ref framework.py:2899).

    Two process-global default programs exist — main + startup — exactly as in
    the reference (framework.py:3813,3846): layer calls append compute ops to
    the main program and parameter-init ops to the startup program.
    """

    def __init__(self):
        self.id = next(_program_ids)
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0          # mutation counter -> executor cache key
        self.random_seed = 0
        # name -> attr dict for program-level metadata (e.g. dist info)
        self._attrs: Dict[str, Any] = {}
        self._current_role: Optional[str] = None

    def _op_role_guard(self, role: str):
        """Ops created inside carry attrs['op_role']=role (ref
        framework.py _op_role / _optimized_guard)."""
        import contextlib

        @contextlib.contextmanager
        def guard():
            prev = self._current_role
            self._current_role = role
            try:
                yield
            finally:
                self._current_role = prev
        return guard()

    # -- blocks --------------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    @property
    def version(self):
        return self._version

    # -- queries -------------------------------------------------------------
    def all_parameters(self) -> List[Variable]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def fingerprint(self) -> Tuple[int, int]:
        """(program id, version) — the executor hashes this EVERY step
        (twice on the fast path), so the tuple is cached and only rebuilt
        after a version bump; ``getattr`` keeps ``Program.__new__``-style
        construction paths (clone/prune/ir) safe without each one having
        to initialize the cache slot."""
        fp = getattr(self, "_fp_cache", None)
        if fp is None or fp[1] != self._version:
            fp = self._fp_cache = (self.id, self._version)
        return fp

    # -- cloning / pruning ---------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program (ref framework.py Program.clone:3098).

        ``for_test=True`` switches ops with an ``is_test`` attr into inference
        mode (dropout off, batch_norm uses running stats), mirroring
        ``_prune_with_input``+``_inference_optimize`` in the reference.
        """
        p = Program.__new__(Program)
        p.id = next(_program_ids)
        p._version = 0
        p.random_seed = self.random_seed
        p._attrs = copy.deepcopy(self._attrs)
        p._current_block_idx = 0
        p._current_role = None
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                nv = Variable(nb, name, shape=v.shape, dtype=v.dtype,
                              type=v.type, persistable=v.persistable,
                              stop_gradient=v.stop_gradient,
                              initializer=v.initializer,
                              is_parameter=v.is_parameter,
                              trainable=v.trainable,
                              regularizer=v.regularizer,
                              need_clip=v.need_clip)
                nv.seq_len_var = v.seq_len_var
                if getattr(v, "is_data", False):
                    nv.is_data = True
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.attrs.get("op_role") in (
                        "backward", "optimize", "lrsched"):
                    # ref framework.py clone docstring: "We will prune the
                    # backward and optimize part of the program when you
                    # use clone after Optimizer.minimize"
                    continue
                attrs = {}
                for k, val in op.attrs.items():
                    if isinstance(val, Block):
                        attrs[k] = p.blocks[val.idx]
                    else:
                        attrs[k] = copy.deepcopy(val)
                if for_test and "is_test" in attrs:
                    attrs["is_test"] = True
                nop = Operator(nb, op.type, None, None, attrs)
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nb.ops.append(nop)
        return p

    def _prune(self, targets: Sequence[Variable]) -> "Program":
        """Keep only ops needed to compute ``targets`` (ref framework/prune.cc).

        Operates on block 0 with a reverse liveness sweep; control-flow ops are
        kept whole (their sub-blocks ride along).
        """
        target_names = {t.name if isinstance(t, Variable) else t for t in targets}
        pruned = self.clone()
        blk = pruned.global_block()
        needed = set(target_names)
        keep: List[Operator] = []
        for op in reversed(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            if needed & set(op.output_arg_names()):
                keep.append(op)
                needed |= set(op.input_arg_names())
        blk.ops = list(reversed(keep))
        pruned._bump_version()
        return pruned

    # -- serialization (stands in for protobuf ProgramDesc bytes) -----------
    def to_dict(self):
        from .. import __version__
        return {"version": PROGRAM_FORMAT_VERSION,
                "framework_version": __version__,
                "random_seed": self.random_seed,
                "blocks": [b.to_dict() for b in self.blocks]}

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @staticmethod
    def parse_from_string(data: bytes) -> "Program":
        d = json.loads(data.decode("utf-8"))
        # ref framework/version.h IsProgramVersionSupported: refuse blobs
        # from a NEWER format (older formats load — fields default)
        fmt = int(d.get("version", 0))
        if fmt > PROGRAM_FORMAT_VERSION:
            raise ValueError(
                f"program blob has format version {fmt}, newer than this "
                f"framework supports ({PROGRAM_FORMAT_VERSION}) — upgrade "
                "paddle_tpu to load it (saved by framework "
                f"{d.get('framework_version', '<unknown>')!r})")
        p = Program.__new__(Program)
        p.id = next(_program_ids)
        p._version = 0
        p.random_seed = d.get("random_seed", 0)
        p._attrs = {}
        p._current_block_idx = 0
        p._current_role = None
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(b)
        for bd, b in zip(d["blocks"], p.blocks):
            for name, vd in bd["vars"].items():
                b.vars[name] = Variable(
                    b, name, shape=vd["shape"], dtype=vd["dtype"],
                    type=vd["type"], persistable=vd["persistable"],
                    stop_gradient=vd["stop_gradient"],
                    is_parameter=vd.get("is_parameter", False),
                    trainable=vd.get("trainable", True))
                if vd.get("is_data"):
                    b.vars[name].is_data = True
            for od in bd["ops"]:
                attrs = {}
                for k, v in od["attrs"].items():
                    if isinstance(v, dict) and "__block__" in v:
                        attrs[k] = p.blocks[v["__block__"]]
                    elif isinstance(v, dict) and "__ndarray__" in v:
                        attrs[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
                    else:
                        attrs[k] = v
                op = Operator(b, od["type"], None, None, attrs)
                op.inputs = {k: list(v) for k, v in od["inputs"].items()}
                op.outputs = {k: list(v) for k, v in od["outputs"].items()}
                b.ops.append(op)
        return p

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for op in b.ops:
                lines.append(f"  {op}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# default program machinery (ref framework.py:3813-3926)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


class program_guard:
    """``with program_guard(main, startup):`` scoped default-program switch
    (ref framework.py:3926)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self.old_main = switch_main_program(self.main)
        if self.startup is not None:
            self.old_startup = switch_startup_program(self.startup)
        return self

    def __exit__(self, *exc):
        switch_main_program(self.old_main)
        if self.startup is not None:
            switch_startup_program(self.old_startup)
        return False


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    """Reference grad-var naming convention (framework/operator.h:57)."""
    return name + GRAD_SUFFIX
