from . import unique_name  # noqa
from .backward import append_backward, calc_gradient, gradients  # noqa
from .core import (Block, Operator, Parameter, Program, Variable,  # noqa
                   VarType, convert_dtype, default_main_program,
                   default_startup_program, grad_var_name, program_guard,
                   switch_main_program, switch_startup_program)
from .executor import Executor  # noqa
from . import ir  # noqa  (Graph/Pass/PassBuilder + fusion & analysis passes)
from .scope import Scope, global_scope, scope_guard  # noqa
