"""Unique name generator (reference ``python/paddle/fluid/unique_name.py``)."""

from __future__ import annotations

import collections
import contextlib


class UniqueNameGenerator:
    def __init__(self):
        self.ids = collections.defaultdict(int)

    def __call__(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{i}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


@contextlib.contextmanager
def guard(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        _generator = old
