"""Program-level autodiff: synthesize grad ops into the Program.

Mirrors ``python/paddle/fluid/backward.py:558`` (append_backward): reverse-walk
the ops that contribute to the loss, ask each op's grad maker for grad op
descs (here ``registry.make_grad_ops`` — generic jax.vjp-backed unless an op
registers a custom maker, standing in for ``core.get_grad_op_desc`` /
``GradOpDescMakerBase``), rename+sum gradients of multi-consumer vars
(ref ``_addup_repetitive_outputs_``), and append the resulting ops to the
block.  Grad vars use the ``<name>@GRAD`` convention
(ref ``framework/operator.h:57``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry
from .core import Block, Operator, Program, Variable, grad_var_name


def _relevant_ops(block: Block, loss: Variable,
                  no_grad_set: Set[str]) -> Tuple[List[int], Set[str]]:
    """Backward slice: indices of ops on a path to ``loss`` plus the set of
    vars that need gradients."""
    needed: Set[str] = {loss.name}
    relevant: List[int] = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if registry.has_op(op.type) and registry.get_op_info(op.type).no_grad:
            continue
        if needed & set(op.output_arg_names()):
            relevant.append(i)
            for n in op.input_arg_names():
                if n and n not in no_grad_set:
                    v = block.var(n) if block.has_var(n) else None
                    if v is not None and v.stop_gradient:
                        continue
                    needed.add(n)
    relevant.reverse()
    return relevant, needed


def append_backward(loss: Variable,
                    parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    callbacks=None) -> List[Tuple[Variable, Variable]]:
    """Append grad ops for ``loss``; return [(param, param@GRAD)] pairs."""
    block = loss.block.program.global_block()
    program = block.program
    no_grad = set(no_grad_set or ())
    for v in block.vars.values():
        if v.stop_gradient and not v.is_parameter:
            no_grad.add(v.name)
        elif v.dtype is not None and v.dtype not in (
                "float16", "bfloat16", "float32", "float64"):
            # integer/bool vars carry no gradient (the reference's
            # OpKernelType dispatch never registers grad kernels for them;
            # under jax they'd surface as float0 tangents)
            no_grad.add(v.name)

    relevant, needed = _relevant_ops(block, loss, no_grad)

    # every op appended below is training-only: tag it so
    # clone(for_test=True) prunes the backward tail (ref OpRole::kBackward)
    with program._op_role_guard("backward"):
        return _append_backward_tagged(block, program, loss, no_grad,
                                       relevant, needed, parameter_list)


def _append_backward_tagged(block, program, loss, no_grad, relevant, needed,
                            parameter_list):
    # seed: d loss / d loss = 1  (ref backward.py _append_loss_ops /
    # ScaleLossGradOpHandle with coeff 1 on a single device)
    loss_g_name = grad_var_name(loss.name)
    block.create_var(name=loss_g_name, shape=loss.shape, dtype=loss.dtype,
                     stop_gradient=True)
    block.append_op(
        "fill_constant", outputs={"Out": [loss_g_name]},
        attrs={"shape": list(loss.shape or ()), "dtype": loss.dtype,
               "value": 1.0})

    # generate grad descs in reverse order
    descs: List[Dict] = []
    have_grad: Set[str] = {loss_g_name}
    for i in reversed(relevant):
        op = block.ops[i]
        # only if some output's grad exists
        if not any(grad_var_name(n) in have_grad
                   for n in op.output_arg_names()):
            continue
        # only if some input needs a grad
        if not any(n in needed and n not in no_grad
                   for n in op.input_arg_names()):
            continue
        for d in registry.make_grad_ops(op, block, no_grad):
            descs.append(d)
            for names in d["outputs"].values():
                for n in names:
                    if n:
                        have_grad.add(n)

    # Resolve grad dataflow: sum parallel contributions (ref backward.py
    # _addup_repetitive_outputs_) AND version in-place redefinitions (ref
    # _rename_grad_ for in-place ops).  A desc that consumes grad name N
    # and produces N again (while_grad on a carried var) REPLACES the
    # value — its output gets a fresh version and later consumers read
    # that version; plain producers of the current version are summands,
    # materialized right before the first desc that reads them.
    ver: Dict[str, int] = {}

    def rd(n):
        v = ver.get(n, 0)
        return n if v == 0 else f"{n}@V{v}"

    # contribs[n]: pending summands of the CURRENT version of grad n —
    # ("site", di, slot, j) for a desc output not yet renamed, or
    # ("value", name) once a reader has materialized the sum.  Within one
    # version every contribution precedes the first reader (descs are
    # generated in reverse op order), so a contribution arriving AFTER a
    # read can only mean the forward program redefined the var in place
    # without a gradient-redefining op — numerically ambiguous, raised
    # loudly below rather than silently mis-summed.
    contribs: Dict[str, List[tuple]] = {}
    sums_before: Dict[int, List[Tuple[str, List[str]]]] = {}
    end_sums: List[Tuple[str, List[str]]] = []
    end_assigns: List[Tuple[str, str]] = []

    def _materialize(n, at_di):
        """Collapse this version's pending summands into one value."""
        entries = contribs.get(n)
        if not entries:
            return
        if len(entries) == 1:
            if entries[0][0] == "site":
                contribs[n] = [("value", rd(n))]
            return
        parts, k = [], 0
        for e in entries:
            if e[0] == "value":
                parts.append(e[1])
            else:
                _, pi, slot, j = e
                pn = f"{rd(n)}@RENAME@{k}"
                k += 1
                descs[pi]["outputs"][slot][j] = pn
                parts.append(pn)
        if at_di is None:
            end_sums.append((rd(n), parts))
        else:
            sums_before.setdefault(at_di, []).append((rd(n), parts))
        contribs[n] = [("value", rd(n))]

    for di, d in enumerate(descs):
        raw_ins = {n for names in d["inputs"].values() for n in names if n}
        for n in raw_ins:
            _materialize(n, di)
        for slot, names in d["inputs"].items():
            d["inputs"][slot] = [rd(n) if n else n for n in names]
        for slot, names in d["outputs"].items():
            for j, n in enumerate(names):
                if not n:
                    continue
                if n in raw_ins and contribs.get(n):
                    # redefinition: new version, sole producer so far
                    ver[n] = ver.get(n, 0) + 1
                    d["outputs"][slot][j] = rd(n)
                    contribs[n] = [("site", di, slot, j)]
                else:
                    entries = contribs.setdefault(n, [])
                    if entries and entries[0][0] == "value":
                        raise ValueError(
                            f"gradient contribution to {n!r} arrives after "
                            "a grad op already read it: the forward "
                            "program overwrites this variable in place "
                            "(e.g. assign with an existing output) between "
                            "reads, which makes its gradient ambiguous — "
                            "write the second value to a fresh variable")
                    d["outputs"][slot][j] = rd(n)
                    entries.append(("site", di, slot, j))

    for n in list(contribs):
        _materialize(n, None)          # unconsumed summands (param grads)
        if rd(n) != n:
            # optimizers look up the canonical <name>@GRAD
            end_assigns.append((n, rd(n)))

    # append to block, materializing grad vars
    def _append_sum(name, parts):
        if not block.has_var(name):
            src = block.var(parts[0]) if block.has_var(parts[0]) else None
            block.create_var(name=name,
                             shape=src.shape if src else None,
                             dtype=src.dtype if src else "float32",
                             stop_gradient=True)
        block.append_op("sum", inputs={"X": parts},
                        outputs={"Out": [name]})

    appended: List[Operator] = []
    for di, d in enumerate(descs):
        for name, parts in sums_before.get(di, []):
            _append_sum(name, parts)
        _ensure_grad_vars(block, d)
        op = Operator(block, d["type"], None, None, d["attrs"])
        op.inputs = d["inputs"]
        op.outputs = d["outputs"]
        block.ops.append(op)
        program._bump_version()
        appended.append(op)
    for name, parts in end_sums:
        _append_sum(name, parts)
    for target, src in end_assigns:
        if not block.has_var(target):
            sv = block.var(src) if block.has_var(src) else None
            block.create_var(name=target,
                             shape=sv.shape if sv else None,
                             dtype=sv.dtype if sv else "float32",
                             stop_gradient=True)
        block.append_op("assign", inputs={"X": [src]},
                        outputs={"Out": [target]})

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [p if isinstance(p, Variable) else block.var(p)
                  for p in parameter_list]
    else:
        params = [p for p in block.all_parameters() if p.trainable]
    result = []
    for p in params:
        gname = grad_var_name(p.name)
        if block.has_var(gname):
            gv = block.var(gname)
            if gv.shape is None:
                gv.shape, gv.dtype = p.shape, p.dtype
            result.append((p, gv))
    return result


def _ensure_grad_vars(block: Block, desc: Dict) -> None:
    """Create Variables for a grad desc's args, inferring metadata from the
    forward var where the @GRAD convention applies."""
    for names in list(desc["inputs"].values()) + list(desc["outputs"].values()):
        for n in names:
            if not n or block.has_var(n):
                continue
            base = n.split("@GRAD")[0] if "@GRAD" in n else None
            if base and block.has_var(base):
                fv = block.var(base)
                block.create_var(name=n, shape=fv.shape, dtype=fv.dtype,
                                 stop_gradient=True)
            else:
                block.create_var(name=n, stop_gradient=True)


def calc_gradient(targets, inputs, target_gradients=None,
                  no_grad_set=None):
    """ref backward.py:820 — gradients of ``targets`` w.r.t. ``inputs``."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("calc_gradient supports a single target")
    append_backward(targets[0], no_grad_set=no_grad_set)
    block = targets[0].block.program.global_block()
    outs = []
    for iv in inputs:
        g = grad_var_name(iv.name)
        outs.append(block.var(g) if block.has_var(g) else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
