"""Graph IR + pass infrastructure (ref SURVEY §2.2, ``paddle/fluid/framework/ir/``).

TPU-native role: in the reference, graph passes are the *primary* optimizer —
fusion passes stitch kernels together because the runtime dispatches one CUDA
kernel per op.  Under XLA the whole block compiles as one computation and the
compiler does the fusing, so these passes are (a) program-level canonicalizers
that produce better-shaped traces (e.g. folding conv+BN at inference time
eliminates the BN params entirely), (b) the analysis substrate (liveness,
inplace pairing) that informs buffer donation, and (c) the user-extensible
rewrite framework (``Pass``/``PassRegistry``/``PassBuilder``) the reference
exposes via ``ir::Pass`` (``ir/pass.h``) and ``BuildStrategy``.

Components mirrored (reference file:line cited per class):
- ``Graph``/``Node``       ← ``ir/graph.{h,cc}``, ``ir/node.{h,cc}``
- ``topology_sort``        ← ``ir/graph_helper.cc TopologySortOperations``
- ``Pass``/``PassRegistry``← ``ir/pass.{h,cc}``
- ``PassBuilder``          ← ``ir/pass_builder.{h,cc}``
- ``PDNode``/``PDPattern``/``GraphPatternDetector``
                           ← ``ir/graph_pattern_detector.{h,cc}``
- fusion passes            ← ``ir/fc_fuse_pass.cc``,
                             ``ir/conv_bn_fuse_pass.cc``,
                             ``ir/fuse_elewise_add_act_pass.cc``
- ``reference_count_pass`` / ``buffer_shared_inplace_pass`` analogs
                           ← ``ir/memory_optimize_pass/``
- ``graph_viz_pass`` (DOT) ← ``ir/graph_viz_pass.cc``
- ``graph_to_program``     ← ``ir/graph_to_program_pass.cc``
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence

from .core import Operator, Program, Variable

# ---------------------------------------------------------------------------
# Graph / Node
# ---------------------------------------------------------------------------

_node_ids = itertools.count()


class Node:
    """Op or var node (ref ``ir/node.h`` Node::Type::kOperation/kVariable).

    Var nodes are SSA: every write to a name creates a fresh var node, so a
    pattern match never confuses a value with its later overwrite (the
    reference gets this from per-definition ``VarHandle`` versions).
    """

    def __init__(self, kind: str, name: str, op: Optional[Operator] = None,
                 var: Optional[Variable] = None):
        self.id = next(_node_ids)
        self.kind = kind                    # "op" | "var"
        self.name = name                    # op type, or var name
        self.op = op                        # Operator (op nodes)
        self.var = var                      # Variable metadata (var nodes)
        self.inputs: List[Node] = []
        self.outputs: List[Node] = []

    def is_op(self, type=None) -> bool:
        if self.kind != "op" or type is None:
            return self.kind == "op"
        if isinstance(type, (tuple, list, set, frozenset)):
            return self.name in type
        return self.name == type

    def is_var(self) -> bool:
        return self.kind == "var"

    @property
    def persistable(self) -> bool:
        return bool(self.var is not None and self.var.persistable)

    def __repr__(self):
        return f"Node#{self.id}({self.kind}:{self.name})"


class Graph:
    """Dependency graph of one block (ref ``ir/graph.h`` ir::Graph).

    Built from block 0 of a Program; ops in other blocks (control-flow
    sub-blocks) ride along opaquely through their Block-valued attrs, exactly
    as the reference keeps sub-graphs inside the op's attribute.
    """

    def __init__(self, program: Program, block_idx: int = 0):
        self.program = program
        self.block_idx = block_idx
        self.attrs: Dict[str, object] = {}
        self.op_nodes: List[Node] = []      # in original program order
        self.var_nodes: List[Node] = []
        block = program.blocks[block_idx]
        latest: Dict[str, Node] = {}        # name -> current SSA def

        def var_meta(name):
            return block.vars.get(name) or (
                block.var(name) if block.has_var(name) else None)

        for op in block.ops:
            op_node = Node("op", op.type, op=op)
            self.op_nodes.append(op_node)
            for name in op.input_arg_names():
                if not name:
                    continue
                v = latest.get(name)
                if v is None:
                    v = Node("var", name, var=var_meta(name))
                    latest[name] = v
                    self.var_nodes.append(v)
                op_node.inputs.append(v)
                v.outputs.append(op_node)
            for name in op.output_arg_names():
                if not name:
                    continue
                v = Node("var", name, var=var_meta(name))
                latest[name] = v
                self.var_nodes.append(v)
                op_node.outputs.append(v)
                v.inputs.append(op_node)

    # -- queries -------------------------------------------------------------
    def all_op_nodes(self) -> List[Node]:
        return list(self.op_nodes)

    def all_var_nodes(self) -> List[Node]:
        return list(self.var_nodes)

    def ops_of_type(self, type: str) -> List[Node]:
        return [n for n in self.op_nodes if n.name == type]

    def num_nodes(self) -> int:
        return len(self.op_nodes) + len(self.var_nodes)

    def topology_sort(self) -> List[Node]:
        """Op nodes in dependency order (ref graph_helper.cc
        TopologySortOperations).  Program order is already topological for a
        straight-line block, but passes may have appended nodes out of order."""
        indeg: Dict[int, int] = {}
        succ: Dict[int, List[Node]] = {}
        for op in self.op_nodes:
            indeg.setdefault(op.id, 0)
            for v in op.outputs:
                for consumer in v.outputs:
                    succ.setdefault(op.id, []).append(consumer)
                    indeg[consumer.id] = indeg.get(consumer.id, 0) + 1
        from collections import deque
        ready = deque(op for op in self.op_nodes if indeg[op.id] == 0)
        order: List[Node] = []
        while ready:
            op = ready.popleft()
            order.append(op)
            for consumer in succ.get(op.id, []):
                indeg[consumer.id] -= 1
                if indeg[consumer.id] == 0:
                    ready.append(consumer)
        if len(order) != len(self.op_nodes):
            raise RuntimeError("graph has a cycle; pass produced invalid IR")
        return order

    # -- mutation (ref graph.h CreateOpNode/CreateVarNode/RemoveNode) --------
    def create_op_node(self, type: str, inputs: Dict[str, List[Node]],
                       outputs: Dict[str, List[Node]],
                       attrs: Optional[dict] = None) -> Node:
        block = self.program.blocks[self.block_idx]
        op = Operator(block, type, attrs=attrs or {})
        op.inputs = {slot: [v.name for v in vs] for slot, vs in inputs.items()}
        op.outputs = {slot: [v.name for v in vs]
                      for slot, vs in outputs.items()}
        node = Node("op", type, op=op)
        for vs in inputs.values():
            for v in vs:
                node.inputs.append(v)
                v.outputs.append(node)
        for vs in outputs.values():
            for v in vs:
                node.outputs.append(v)
                v.inputs.append(node)
        self.op_nodes.append(node)
        return node

    def create_var_node(self, name: str, shape=None, dtype=None,
                        persistable: bool = False) -> Node:
        block = self.program.blocks[self.block_idx]
        var = block.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=persistable)
        node = Node("var", var.name, var=var)
        self.var_nodes.append(node)
        return node

    def safe_remove_nodes(self, nodes: Sequence[Node]) -> None:
        doomed = {n.id for n in nodes}
        for n in nodes:
            if n.kind == "op":
                self.op_nodes = [o for o in self.op_nodes if o.id != n.id]
            else:
                self.var_nodes = [v for v in self.var_nodes if v.id != n.id]
        for n in itertools.chain(self.op_nodes, self.var_nodes):
            n.inputs = [i for i in n.inputs if i.id not in doomed]
            n.outputs = [o for o in n.outputs if o.id not in doomed]

    # -- export (ref ir/graph_to_program_pass.cc) ----------------------------
    def to_program(self) -> Program:
        """Rebuild a Program: block 0 from this graph (topo order), other
        blocks copied from the source so Block-valued attrs stay valid."""
        src = self.program
        out = src.clone()
        blk = out.global_block()
        # vars already cloned; add any pass-created vars
        for v in self.var_nodes:
            if v.var is not None and v.name not in blk.vars:
                blk.create_var(name=v.name, shape=v.var.shape,
                               dtype=v.var.dtype,
                               persistable=v.var.persistable)
        blk.ops = []
        for op_node in self.topology_sort():
            op = op_node.op
            attrs = {}
            for k, val in op.attrs.items():
                # remap sub-block refs into the cloned program
                from .core import Block
                attrs[k] = out.blocks[val.idx] if isinstance(val, Block) \
                    else val
            nop = Operator(blk, op.type, None, None, attrs)
            nop.inputs = {k: list(v) for k, v in op.inputs.items()}
            nop.outputs = {k: list(v) for k, v in op.outputs.items()}
            blk.ops.append(nop)
        # sub-block rewrites recorded by passes (the Graph itself models
        # only one block): dead_op_eliminate stores the per-sub-block
        # dead op indices here and materialization applies them
        sub_dead = self.attrs.get("dead_subblock_ops")
        if sub_dead:
            prune_subblock_ops(out, sub_dead)
        out._bump_version()
        return out

    def apply_to_program(self) -> Program:
        """Write the rewritten block 0 back INTO the source program object.

        For train-time passes that must run between model build and
        ``minimize()``: append_backward goes to ``loss.block.program`` but
        ``apply_gradients`` targets ``default_main_program()`` — a cloned
        program from :meth:`to_program` silently splits the two (grads in
        the clone, optimizer ops in the default → parameters never
        update).  Mutating the original keeps every later stage on one
        program."""
        rebuilt = self.to_program()
        src = self.program
        blk = src.global_block()
        new_blk = rebuilt.global_block()
        for name, v in new_blk.vars.items():
            if name not in blk.vars:
                blk.vars[name] = v
                v.block = blk
        # retarget sub-block attrs back at the source program's blocks
        from .core import Block
        ops = []
        referenced = set()
        for op in new_blk.ops:
            for k, val in op.attrs.items():
                if isinstance(val, Block):
                    op.attrs[k] = src.blocks[val.idx]
            op.block = blk
            ops.append(op)
            referenced.update(op.input_arg_names())
            referenced.update(op.output_arg_names())
        blk.ops = ops
        # drop vars the rewrite orphaned (e.g. the fused-away conv outputs)
        # — phantom unwritten non-persistables would confuse later Graph
        # builds / serialization; persistables and parameters stay (their
        # values live in the scope)
        for name in list(blk.vars):
            v = blk.vars[name]
            if name not in referenced and not v.persistable and \
                    not getattr(v, "is_parameter", False):
                del blk.vars[name]
        # sub-block rewrites (see to_program) apply to the source too
        sub_dead = self.attrs.get("dead_subblock_ops")
        if sub_dead:
            prune_subblock_ops(src, sub_dead)
        src._bump_version()
        return src


# ---------------------------------------------------------------------------
# Pass framework (ref ir/pass.h, ir/pass_builder.h)
# ---------------------------------------------------------------------------

class Pass:
    """Base pass: override ``apply_impl(graph) -> graph``.

    The ``protected`` attr (set of var names) marks values an enclosing
    executor will fetch: rewrites must not remove their defining ops (the
    reference marks fetched vars in the graph before applying passes —
    parallel_executor.cc keeps FetchOpHandles as graph roots)."""

    name = "pass"

    def __init__(self, **attrs):
        self.attrs = attrs

    def protected_vars(self) -> frozenset:
        return frozenset(self.get("protected") or ())

    def set(self, key, value):
        self.attrs[key] = value
        return self

    def get(self, key, default=None):
        return self.attrs.get(key, default)

    def apply(self, graph: Graph) -> Graph:
        out = self.apply_impl(graph)
        return graph if out is None else out

    def apply_impl(self, graph: Graph) -> Optional[Graph]:
        raise NotImplementedError


_PASS_REGISTRY: Dict[str, Callable[..., Pass]] = {}


def register_pass(name: str):
    """``REGISTER_PASS`` (ref ir/pass.h:195)."""
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return deco


def get_pass(name: str, **attrs) -> Pass:
    if name not in _PASS_REGISTRY:
        raise KeyError(f"no pass registered under {name!r}; "
                       f"have {sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name](**attrs)


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


class PassBuilder:
    """Ordered pass pipeline (ref ir/pass_builder.h PassBuilder)."""

    def __init__(self, names: Optional[Sequence[str]] = None):
        self._passes: List[Pass] = [get_pass(n) for n in (names or [])]

    def append_pass(self, name: str, **attrs) -> Pass:
        p = get_pass(name, **attrs)
        self._passes.append(p)
        return p

    def insert_pass(self, idx: int, name: str, **attrs) -> Pass:
        p = get_pass(name, **attrs)
        self._passes.insert(idx, p)
        return p

    def remove_pass(self, idx: int) -> None:
        del self._passes[idx]

    def all_passes(self) -> List[Pass]:
        return list(self._passes)

    def apply(self, graph: Graph) -> Graph:
        for p in self._passes:
            graph = p.apply(graph)
        return graph


def apply_passes(program: Program, names: Sequence[str],
                 **attrs) -> Program:
    """Convenience: Program → Graph → passes → Program."""
    graph = Graph(program)
    for n in names:
        graph = get_pass(n, **attrs).apply(graph)
    return graph.to_program()


# ---------------------------------------------------------------------------
# Pattern detector (ref ir/graph_pattern_detector.{h,cc})
# ---------------------------------------------------------------------------

class PDNode:
    """One slot of a pattern: predicate + role flags (ref PDNode)."""

    def __init__(self, pattern: "PDPattern", name: str, kind: str,
                 op_type: Optional[str] = None,
                 predicate: Optional[Callable[[Node], bool]] = None,
                 persistable: Optional[bool] = None):
        self.pattern = pattern
        self.pd_name = name
        self.kind = kind
        self.op_type = op_type
        self.predicate = predicate
        self.persistable = persistable
        self.intermediate = False

    def as_intermediate(self) -> "PDNode":
        """Matched nodes are consumed by the rewrite (removed)."""
        self.intermediate = True
        return self

    def matches(self, node: Node) -> bool:
        if node.kind != self.kind:
            return False
        if self.op_type is not None and node.name != self.op_type:
            return False
        if self.persistable is not None and node.kind == "var" and \
                node.persistable != self.persistable:
            return False
        return self.predicate is None or self.predicate(node)


class PDPattern:
    """A small graph of PDNodes with edges (ref PDPattern)."""

    def __init__(self):
        self.nodes: List[PDNode] = []
        self.edges: List[tuple] = []        # (from PDNode, to PDNode)

    def new_op(self, op_type: str, name: Optional[str] = None,
               predicate=None) -> PDNode:
        n = PDNode(self, name or op_type, "op", op_type=op_type,
                   predicate=predicate)
        self.nodes.append(n)
        return n

    def new_var(self, name: str, persistable: Optional[bool] = None,
                predicate=None) -> PDNode:
        n = PDNode(self, name, "var", predicate=predicate,
                   persistable=persistable)
        self.nodes.append(n)
        return n

    def link(self, frm: PDNode, to: PDNode) -> None:
        self.edges.append((frm, to))


class GraphPatternDetector:
    """Backtracking subgraph matcher.  The reference builds candidate sets
    per PDNode then prunes by edge consistency
    (graph_pattern_detector.cc MarkPDNodesInGraph/DetectPatterns); pattern
    sizes are tiny (<10 nodes) so plain DFS with injectivity is equivalent
    and simpler."""

    def __init__(self, pattern: PDPattern):
        self.pattern = pattern

    def __call__(self, graph: Graph) -> List[Dict[PDNode, Node]]:
        pat = self.pattern
        all_nodes = graph.all_op_nodes() + graph.all_var_nodes()
        candidates = {pd: [n for n in all_nodes if pd.matches(n)]
                      for pd in pat.nodes}
        order = sorted(pat.nodes, key=lambda pd: len(candidates[pd]))
        matches: List[Dict[PDNode, Node]] = []
        used_ids = set()                    # no overlapping rewrites

        def edges_ok(assign: Dict[PDNode, Node]) -> bool:
            for frm, to in pat.edges:
                if frm in assign and to in assign:
                    if assign[to] not in assign[frm].outputs:
                        return False
            return True

        def dfs(i: int, assign: Dict[PDNode, Node]):
            if i == len(order):
                if not any(n.id in used_ids for n in assign.values()):
                    matches.append(dict(assign))
                    used_ids.update(
                        n.id for pd, n in assign.items()
                        if pd.intermediate or pd.kind == "op")
                return
            pd = order[i]
            taken = {n.id for n in assign.values()}
            for cand in candidates[pd]:
                if cand.id in taken:
                    continue
                assign[pd] = cand
                if edges_ok(assign):
                    dfs(i + 1, assign)
                del assign[pd]

        dfs(0, {})
        return matches


# ---------------------------------------------------------------------------
# Fusion passes
# ---------------------------------------------------------------------------

@register_pass("fc_fuse_pass")
class FCFusePass(Pass):
    """mul(X,W) + elementwise_add(·,b) [+ act] → one ``fc`` op
    (ref ir/fc_fuse_pass.cc).  Under XLA the fusion itself is free; the win
    is a canonical single node for later passes (quant, viz, stats)."""

    ACTS = ("relu", "tanh", "sigmoid", "gelu")

    def apply_impl(self, graph: Graph) -> Graph:
        pat = PDPattern()
        mul = pat.new_op("mul")
        mul_out = pat.new_var("mul_out").as_intermediate()
        add = pat.new_op("elementwise_add")
        bias = pat.new_var("bias", persistable=True)
        add_out = pat.new_var("add_out")
        pat.link(mul, mul_out)
        pat.link(mul_out, add)
        pat.link(bias, add)
        pat.link(add, add_out)
        protected = self.protected_vars()
        count = 0
        for m in GraphPatternDetector(pat)(graph):
            # mul_out must feed ONLY the add (no other consumer may lose
            # it), and must not be a fetch target
            if len(m[mul_out].outputs) != 1 or \
                    m[mul_out].name in protected:
                continue
            mul_op, add_op = m[mul], m[add]
            # bind operands by SLOT, not by persistability: fc is X@W, so
            # Input must be mul's X and W its Y (which must be a weight)
            by_name = {v.name: v for v in mul_op.inputs}
            x_name = mul_op.op.input("X")[0]
            w_name = mul_op.op.input("Y")[0]
            x_node, w_node = by_name.get(x_name), by_name.get(w_name)
            if x_node is None or w_node is None or not w_node.persistable:
                continue
            out_node = m[add_out]
            act_type = ""
            doomed = [mul_op, add_op, m[mul_out]]
            # optional activation directly consuming add_out
            consumers = out_node.outputs
            if len(consumers) == 1 and consumers[0].is_op() and \
                    consumers[0].name in self.ACTS and \
                    out_node.name not in protected:
                act_op = consumers[0]
                act_type = act_op.name
                doomed += [act_op, out_node]
                out_node = act_op.outputs[0]
            graph.create_op_node(
                "fc",
                inputs={"Input": [x_node], "W": [w_node],
                        "Bias": [m[bias]]},
                outputs={"Out": [out_node]},
                attrs={"in_num_col_dims":
                       mul_op.op.attrs.get("x_num_col_dims", 1),
                       "activation_type": act_type})
            graph.safe_remove_nodes(doomed)
            count += 1
        graph.attrs["fc_fuse_count"] = count
        return graph


@register_pass("attention_fuse_pass")
class AttentionFusePass(Pass):
    """matmul(Q,Kᵀ,α) [+ mask add] → softmax → matmul(·,V)  ⇒  one
    ``flash_attention`` op.

    TPU-native pass with no reference counterpart: saved inference
    artifacts built with the dense attention recipe (ref
    dist_transformer.py scaled_dot_product_attention — materializes
    [b,h,T,T] scores) get rewritten onto the Pallas flash kernel, which
    wins from T≈1024 and is the only runnable path beyond ~8k
    (models/transformer.py attn_impl="auto" makes the same call at build
    time; this pass makes it at LOAD time for existing artifacts).
    Set ``min_seq_len`` (default 1024) to control the crossover.

    Matched shapes of the chain:
    - bidirectional self-attention (no mask add);
    - masked attention — the additive [*,*,Tq,Tk] bias rides into the
      kernel's Bias input;
    - CAUSAL decoder self-attention: when ``scope=`` is given (the
      predictor passes its loaded scope) and the bias is a persistable
      frozen causal mask (zeros on/below the diagonal, large-negative
      above), the mask is dropped and the op gets ``causal=True`` — the
      kernel then skips the masked key blocks outright (~2× at long T)
      instead of reading a [T,T] bias;
    - cross-attention (decoder→encoder): Tq and Tk differ; the kernel is
      rectangular, so the same pattern fuses with no extra handling."""

    @staticmethod
    def _is_frozen_causal_mask(arr) -> bool:
        """True for [*..,T,T] masks with ~0 on/below the diagonal and a
        large negative constant strictly above (the dist_transformer.py
        recipe freezes exactly this into decoder artifacts)."""
        import numpy as np
        if arr.ndim < 2 or arr.shape[-1] != arr.shape[-2]:
            return False
        t = arr.shape[-1]
        m = arr.reshape(-1, t, t)
        if not np.allclose(m, m[0], atol=1e-6):
            return False       # must be the same mask for every batch/head
        low = np.tril(m[0])
        up = m[0][np.triu_indices(t, k=1)]
        return (np.allclose(low, 0.0, atol=1e-6)
                and up.size > 0 and bool((up <= -1e4).all()))

    def apply_impl(self, graph: Graph) -> Graph:
        min_seq = int(self.get("min_seq_len", 1024) or 0)
        protected = self.protected_vars()
        count = 0
        for mm1 in list(graph.ops_of_type("matmul")):
            if mm1 not in graph.op_nodes:
                continue
            a = mm1.op.attrs
            if not a.get("transpose_Y") or a.get("transpose_X"):
                continue
            scores = mm1.outputs[0] if mm1.outputs else None
            if scores is None or len(scores.outputs) != 1 or \
                    scores.name in protected:
                continue
            # optional additive mask between scores and softmax
            nxt = scores.outputs[0]
            bias_node, doomed_mask = None, []
            if nxt.is_op("elementwise_add"):
                add = nxt
                m_out = add.outputs[0] if add.outputs else None
                if m_out is None or len(m_out.outputs) != 1 or \
                        m_out.name in protected:
                    continue
                by_name = {v.name: v for v in add.inputs}
                x_name = add.op.input("X")[0]
                y_name = add.op.input("Y")[0]
                if by_name.get(x_name) is not scores:
                    continue
                bias_node = by_name.get(y_name)
                doomed_mask = [add, m_out]
                nxt = m_out.outputs[0]
            if not nxt.is_op("softmax"):
                continue
            sm = nxt
            # flash_attention normalizes over the last (key) axis of
            # rank-4 [B,H,T,D] operands; a softmax over any other axis
            # must stay on the dense path (the lowering honors axis —
            # ops/nn_ops.py softmax)
            sm_axis = sm.op.attrs.get("axis", -1)
            probs = sm.outputs[0] if sm.outputs else None
            if probs is None or len(probs.outputs) != 1 or \
                    probs.name in protected:
                continue
            mm2 = probs.outputs[0]
            if not mm2.is_op("matmul"):
                continue
            a2 = mm2.op.attrs
            if a2.get("transpose_X") or a2.get("transpose_Y") or \
                    a2.get("alpha", 1.0) != 1.0:
                continue
            if mm2.op.input("X")[0] != probs.name:
                continue
            # bind Q, K, V var nodes by slot
            q_node = next((v for v in mm1.inputs
                           if v.name == mm1.op.input("X")[0]), None)
            k_node = next((v for v in mm1.inputs
                           if v.name == mm1.op.input("Y")[0]), None)
            v_node = next((v for v in mm2.inputs
                           if v.name == mm2.op.input("Y")[0]), None)
            if q_node is None or k_node is None or v_node is None:
                continue
            # crossover gate: flash wins from ~1k tokens; shorter
            # sequences keep XLA's dense attention
            shape = getattr(q_node.var, "shape", None)
            if shape is None or len(shape) != 4 or shape[-2] is None:
                continue
            if shape[-2] != -1 and shape[-2] < min_seq:
                continue
            # operand-rank + softmax-axis gates: the kernel is rank-4,
            # last-axis only
            if any(len(getattr(n.var, "shape", None) or ()) != 4
                   for n in (k_node, v_node)):
                continue
            scores_rank = len(getattr(scores.var, "shape", None) or shape)
            if sm_axis not in (-1, scores_rank - 1):
                continue
            causal = False
            if bias_node is not None:
                # the flash kernel takes [*,*,Tq,Tk]-shaped biases; the
                # [B,1,1,Tk] padding-mask form would need an explicit
                # broadcast — keep those on the dense path
                bshape = getattr(bias_node.var, "shape", None)
                if bshape is None or len(bshape) < 2 or \
                        bshape[-2] in (1, None):
                    continue
                # a frozen causal mask becomes causal=True with no Bias:
                # the kernel skips masked key blocks instead of reading
                # a [T,T] tensor of -1e9s
                scope = self.get("scope")
                if scope is not None and \
                        getattr(bias_node.var, "persistable", False) and \
                        not bias_node.inputs:
                    try:
                        val = scope.find_var(bias_node.name)
                    except Exception:
                        val = None
                    if val is not None:
                        import numpy as np
                        if self._is_frozen_causal_mask(np.asarray(val)):
                            causal = True
            inputs = {"Q": [q_node], "K": [k_node], "V": [v_node]}
            if bias_node is not None and not causal:
                inputs["Bias"] = [bias_node]
            elif causal and len(bias_node.outputs) == 1 and \
                    bias_node.name not in protected:
                # mask var fed only this add: drop the orphan node too
                doomed_mask.append(bias_node)
            out_node = mm2.outputs[0]
            graph.create_op_node(
                "flash_attention", inputs=inputs,
                outputs={"Out": [out_node]},
                attrs={"sm_scale": float(a.get("alpha", 1.0)),
                       "causal": causal})
            graph.safe_remove_nodes(
                [mm1, scores, sm, probs, mm2] + doomed_mask)
            count += 1
        graph.attrs["attention_fuse_count"] = count
        return graph


@register_pass("fuse_elewise_add_act_pass")
class FuseElewiseAddActPass(Pass):
    """elementwise_add + activation → fused_elemwise_activation
    (ref ir/fuse_elewise_add_act_pass.cc)."""

    ACTS = ("relu", "scale", "tanh", "sigmoid", "gelu")

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for add in list(graph.ops_of_type("elementwise_add")):
            if add not in graph.op_nodes:
                continue
            out = add.outputs[0] if add.outputs else None
            if out is None or len(out.outputs) != 1 or \
                    out.name in protected:
                continue
            act = out.outputs[0]
            if not act.is_op() or act.name not in self.ACTS:
                continue
            # bind by slot: elementwise broadcast is X-major
            by_name = {v.name: v for v in add.inputs}
            try:
                xs = [by_name[add.op.input("X")[0]],
                      by_name[add.op.input("Y")[0]]]
            except (KeyError, IndexError):
                continue
            extra = {}
            if act.name == "scale":
                extra = {"scale": act.op.attrs.get("scale", 1.0),
                         "bias": act.op.attrs.get("bias", 0.0),
                         "bias_after_scale":
                         act.op.attrs.get("bias_after_scale", True)}
            graph.create_op_node(
                "fused_elemwise_activation",
                inputs={"X": [xs[0]], "Y": [xs[1]]},
                outputs={"Out": [act.outputs[0]]},
                attrs={"functor_list": ["elementwise_add", act.name],
                       "axis": add.op.attrs.get("axis", -1), **extra})
            graph.safe_remove_nodes([add, act, out])
            count += 1
        graph.attrs["fuse_elewise_add_act_count"] = count
        return graph


@register_pass("conv_bn_train_fuse_pass")
class ConvBNTrainFusePass(Pass):
    """conv2d(1x1) + batch_norm(TRAIN) [+ relu] → ``fused_conv1x1_bn``.

    TPU-native TRAINING-time fusion with no reference counterpart (the
    reference's conv_bn_fuse_pass.cc handles inference only — batch
    statistics can't fold into weights).  The fused op's Pallas matmul
    accumulates the BN sums in the conv's own output pass, deleting the
    separate stat-reduction read of the (huge) conv output
    (ops/conv_bn_ops.py; measured deltas in RN50_ABLATION.md)."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for bn in list(graph.ops_of_type("batch_norm")):
            if bn not in graph.op_nodes:
                continue
            a = bn.op.attrs
            if a.get("is_test") or a.get("use_global_stats"):
                continue
            if a.get("data_layout", "NCHW") != "NCHW":
                continue
            by_name = {v.name: v for v in bn.inputs}
            x_in = by_name.get(bn.op.input("X")[0])
            if x_in is None or not x_in.inputs or \
                    not x_in.inputs[0].is_op("conv2d"):
                continue
            if len(x_in.outputs) != 1 or x_in.name in protected:
                continue                     # conv output must feed BN only
            conv = x_in.inputs[0]
            ca = conv.op.attrs
            strides = ca.get("strides", [1, 1])
            if ca.get("groups", 1) != 1 or \
                    any(p != 0 for p in ca.get("paddings", [0, 0])) or \
                    any(d != 1 for d in ca.get("dilations", [1, 1])) or \
                    strides[0] != strides[1]:
                continue
            w_node = next((v for v in conv.inputs
                           if v.name == conv.op.input("Filter")[0]), None)
            x_node = next((v for v in conv.inputs
                           if v.name == conv.op.input("Input")[0]), None)
            if w_node is None or x_node is None:
                continue
            wshape = getattr(w_node.var, "shape", None)
            if not wshape or len(wshape) != 4 or wshape[2] != 1 or \
                    wshape[3] != 1:
                continue
            if conv.op.input("Bias"):
                continue
            y_node = next((v for v in bn.outputs
                           if v.name in bn.op.output("Y")), None)
            if y_node is None:
                continue
            # fold a following exclusive relu into the act attr (never
            # when the BN output itself is fetched/protected)
            act, doomed_act = "", []
            if len(y_node.outputs) == 1 and \
                    y_node.outputs[0].is_op("relu") and \
                    y_node.name not in protected:
                relu = y_node.outputs[0]
                act = "relu"
                out_node = relu.outputs[0]
                doomed_act = [relu, y_node]
            else:
                out_node = y_node
            outs = {"Y": [out_node]}
            for slot in ("MeanOut", "VarianceOut", "SavedMean",
                         "SavedVariance"):
                names = bn.op.output(slot)
                if names:
                    node = next((v for v in bn.outputs
                                 if v.name in names), None)
                    if node is not None:
                        outs[slot] = [node]
            graph.create_op_node(
                "fused_conv1x1_bn",
                inputs={"X": [x_node], "Filter": [w_node],
                        "Scale": [by_name[bn.op.input("Scale")[0]]],
                        "Bias": [by_name[bn.op.input("Bias")[0]]],
                        "Mean": [by_name[bn.op.input("Mean")[0]]],
                        "Variance": [by_name[bn.op.input("Variance")[0]]]},
                outputs=outs,
                attrs={"momentum": a.get("momentum", 0.9),
                       "epsilon": a.get("epsilon", 1e-5),
                       "act": act, "stride": int(strides[0]),
                       "is_test": False,
                       "use_global_stats": False})
            graph.safe_remove_nodes([conv, x_in, bn] + doomed_act)
            count += 1
        graph.attrs["conv_bn_train_fuse_count"] = count
        return graph


@register_pass("repeated_fc_relu_fuse_pass")
class RepeatedFCReluFusePass(Pass):
    """Chains of fc(act=relu) → one ``fusion_repeated_fc_relu``
    (ref ir/fc_gru_fuse... family; fused op:
    fused/fusion_repeated_fc_relu_op.cc).  Runs after fc_fuse_pass, which
    produces the canonical fc nodes this pass chains."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        consumed = set()
        for fc in list(graph.ops_of_type("fc")):
            if fc not in graph.op_nodes or fc in consumed:
                continue
            if fc.op.attrs.get("activation_type") != "relu":
                continue
            # only chain HEADS: input not itself produced by a relu-fc
            x_node = next((v for v in fc.inputs
                           if v.name == fc.op.input("Input")[0]), None)
            if x_node is None:
                continue
            if x_node.inputs and x_node.inputs[0].is_op("fc") and \
                    x_node.inputs[0].op.attrs.get("activation_type") == \
                    "relu":
                continue
            chain = [fc]
            while True:
                out = chain[-1].outputs[0]
                if len(out.outputs) != 1 or out.name in protected:
                    break
                nxt = out.outputs[0]
                if not nxt.is_op("fc") or \
                        nxt.op.attrs.get("activation_type") != "relu" or \
                        nxt.op.input("Input")[0] != out.name:
                    break
                chain.append(nxt)
            if len(chain) < 2:
                continue
            ws, bs, doomed = [], [], []
            ok = True
            for i, node in enumerate(chain):
                by_name = {v.name: v for v in node.inputs}
                w = by_name.get(node.op.input("W")[0])
                b = by_name.get(node.op.input("Bias")[0]) \
                    if node.op.input("Bias") else None
                if w is None or b is None:
                    ok = False
                    break
                ws.append(w)
                bs.append(b)
                doomed.append(node)
                if i < len(chain) - 1:
                    doomed.append(node.outputs[0])
            if not ok:
                continue
            out_node = chain[-1].outputs[0]
            graph.create_op_node(
                "fusion_repeated_fc_relu",
                inputs={"X": [x_node], "W": ws, "Bias": bs},
                outputs={"Out": [out_node]}, attrs={})
            graph.safe_remove_nodes(doomed)
            consumed.update(chain)
            count += 1
        graph.attrs["repeated_fc_relu_fuse_count"] = count
        return graph


@register_pass("squared_mat_sub_fuse_pass")
class SquaredMatSubFusePass(Pass):
    """square(X·Y) − square(X)·square(Y) [→ scale] → one
    ``fusion_squared_mat_sub`` (ref ir/squared_mat_sub_fuse_pass.cc —
    the MatchMatrix/pyramid-DNN serving pattern)."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for sub in list(graph.ops_of_type("elementwise_sub")):
            if sub not in graph.op_nodes:
                continue
            by_name = {v.name: v for v in sub.inputs}
            lhs = by_name.get(sub.op.input("X")[0])
            rhs = by_name.get(sub.op.input("Y")[0])
            if lhs is None or rhs is None or not lhs.inputs or \
                    not rhs.inputs:
                continue
            sq_xy, mm2 = lhs.inputs[0], rhs.inputs[0]
            if not sq_xy.is_op("square") or not mm2.is_op("matmul"):
                continue
            mm1_out = sq_xy.inputs[0]
            if not mm1_out.inputs or not mm1_out.inputs[0].is_op("matmul"):
                continue
            mm1 = mm1_out.inputs[0]
            a1, a2 = mm1.op.attrs, mm2.op.attrs
            if any(a.get("transpose_X") or a.get("transpose_Y") or
                   a.get("alpha", 1.0) != 1.0 for a in (a1, a2)):
                continue
            # mm2's operands must be square(x), square(y) of mm1's operands
            m1n = {v.name: v for v in mm1.inputs}
            x_node = m1n.get(mm1.op.input("X")[0])
            y_node = m1n.get(mm1.op.input("Y")[0])
            m2n = {v.name: v for v in mm2.inputs}
            sqx_v = m2n.get(mm2.op.input("X")[0])
            sqy_v = m2n.get(mm2.op.input("Y")[0])
            if None in (x_node, y_node, sqx_v, sqy_v):
                continue
            if not sqx_v.inputs or not sqx_v.inputs[0].is_op("square") or \
                    not sqy_v.inputs or not sqy_v.inputs[0].is_op("square"):
                continue
            sqx_op, sqy_op = sqx_v.inputs[0], sqy_v.inputs[0]
            if sqx_op.inputs[0] is not x_node or \
                    sqy_op.inputs[0] is not y_node:
                continue
            inter = [mm1_out, lhs, rhs, sqx_v, sqy_v]
            if any(len(v.outputs) != 1 or v.name in protected
                   for v in inter):
                continue
            out_node = sub.outputs[0]
            scalar = 1.0
            doomed_scale = []
            if len(out_node.outputs) == 1 and out_node.name not in \
                    protected and out_node.outputs[0].is_op("scale"):
                sc = out_node.outputs[0]
                if sc.op.attrs.get("bias", 0.0) == 0.0:
                    scalar = float(sc.op.attrs.get("scale", 1.0))
                    doomed_scale = [sc, out_node]
                    out_node = sc.outputs[0]
            graph.create_op_node(
                "fusion_squared_mat_sub",
                inputs={"X": [x_node], "Y": [y_node]},
                outputs={"Out": [out_node]}, attrs={"scalar": scalar})
            graph.safe_remove_nodes(
                [mm1, mm1_out, sq_xy, lhs, sqx_op, sqx_v, sqy_op, sqy_v,
                 mm2, rhs, sub] + doomed_scale)
            count += 1
        graph.attrs["squared_mat_sub_fuse_count"] = count
        return graph


@register_pass("transpose_flatten_concat_fuse_pass")
class TransposeFlattenConcatFusePass(Pass):
    """N × (transpose2 → flatten2) → concat ⇒ one
    ``fusion_transpose_flatten_concat``
    (ref ir/transpose_flatten_concat_fuse_pass.cc — the detection-head
    serving pattern)."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for cc in list(graph.ops_of_type("concat")):
            if cc not in graph.op_nodes:
                continue
            srcs, doomed, perms = [], [cc], []
            ok = True
            for v in cc.inputs:
                if v.name in protected or len(v.outputs) != 1 or \
                        not v.inputs or not v.inputs[0].is_op(
                            ("flatten2", "flatten")):
                    ok = False
                    break
                fl = v.inputs[0]
                if fl.op.attrs.get("axis", 1) != 1:
                    ok = False
                    break
                fv = next((u for u in fl.inputs
                           if u.name == fl.op.input("X")[0]), None)
                if fv is None or len(fv.outputs) != 1 or \
                        fv.name in protected or not fv.inputs or \
                        not fv.inputs[0].is_op(("transpose2", "transpose")):
                    ok = False
                    break
                tr = fv.inputs[0]
                perms.append(tuple(tr.op.attrs.get("axis", [])))
                src = next((u for u in tr.inputs
                            if u.name == tr.op.input("X")[0]), None)
                # transpose2/flatten2 emit XShape side outputs: doom the
                # unconsumed ones with their producers (no orphans)
                extra = [o for node in (tr, fl) for o in node.outputs
                         if o is not fv and o is not v]
                if src is None or any(
                        o.outputs or o.name in protected for o in extra):
                    ok = False
                    break
                srcs.append(src)
                doomed += [fl, v, tr, fv] + extra
            if not ok or len(srcs) < 2 or len(set(perms)) != 1:
                continue
            out_node = cc.outputs[0]
            graph.create_op_node(
                "fusion_transpose_flatten_concat",
                inputs={"X": srcs}, outputs={"Out": [out_node]},
                attrs={"trans_axis": list(perms[0]),
                       "concat_axis": cc.op.attrs.get("axis", 1)})
            graph.safe_remove_nodes(doomed)
            count += 1
        graph.attrs["transpose_flatten_concat_fuse_count"] = count
        return graph


@register_pass("seqpool_concat_fuse_pass")
class SeqpoolConcatFusePass(Pass):
    """N × sequence_pool → concat ⇒ one ``fusion_seqpool_concat``
    (ref ir/seqpool_concat_fuse_pass.cc — the CTR/recall serving
    pattern)."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for cc in list(graph.ops_of_type("concat")):
            if cc not in graph.op_nodes:
                continue
            if cc.op.attrs.get("axis", 1) not in (1, -1):
                continue
            srcs, doomed, ptypes = [], [cc], set()
            ok = True
            for v in cc.inputs:
                if v.name in protected or len(v.outputs) != 1 or \
                        not v.inputs or \
                        not v.inputs[0].is_op("sequence_pool"):
                    ok = False
                    break
                sp = v.inputs[0]
                if sp.op.input("SeqLen"):
                    ok = False     # per-branch lengths stay unfused
                    break
                ptypes.add(sp.op.attrs.get("pooltype", "AVERAGE").upper())
                src = next((u for u in sp.inputs
                            if u.name == sp.op.input("X")[0]), None)
                extra = [o for o in sp.outputs if o is not v]
                if src is None or any(
                        o.outputs or o.name in protected for o in extra):
                    ok = False   # MaxIndex consumed/fetched: stay unfused
                    break
                srcs.append(src)
                doomed += [sp, v] + extra
            if not ok or len(srcs) < 2 or len(ptypes) != 1:
                continue
            out_node = cc.outputs[0]
            graph.create_op_node(
                "fusion_seqpool_concat",
                inputs={"X": srcs}, outputs={"Out": [out_node]},
                attrs={"pooltype": next(iter(ptypes))})
            graph.safe_remove_nodes(doomed)
            count += 1
        graph.attrs["seqpool_concat_fuse_count"] = count
        return graph


def _sole_producer(var_node, op_type):
    """The op producing ``var_node`` iff it is of ``op_type`` and the var
    has no other consumer-visible role (single producer is structural)."""
    if not var_node.inputs or not var_node.inputs[0].is_op(op_type):
        return None
    return var_node.inputs[0]


def _input_node(op_node, slot, i=0):
    names = op_node.op.input(slot)
    if not names or i >= len(names):
        return None
    return next((v for v in op_node.inputs if v.name == names[i]), None)


def _output_node(op_node, slot, i=0):
    names = op_node.op.output(slot)
    if not names or i >= len(names):
        return None
    return next((v for v in op_node.outputs if v.name == names[i]), None)


def _referenced_outside_block0(program, name: str) -> bool:
    """True if any op in a control-flow sub-block (block idx > 0) touches
    ``name`` — the block-0 Graph cannot see those consumers, so params they
    share must survive block-0 rewrites."""
    for blk in program.blocks[1:]:
        for op in blk.ops:
            if name in op.input_arg_names() or \
                    name in op.output_arg_names():
                return True
    return False


def _match_fc_proj(g, protected):
    """Match the fc producing ``g``'s Input projection (the shared prefix
    of the fc+rnn fusion family).  Returns (fc, proj, x, w, bias) or
    None; fc must be act-free with in_num_col_dims=2 (keeps the
    [b, t, gates] layout) and a persistable weight."""
    proj = _input_node(g, "Input")
    if proj is None or proj.name in protected or len(proj.outputs) != 1:
        return None
    fc = _sole_producer(proj, "fc")
    if fc is None or fc.op.attrs.get("activation_type") or \
            int(fc.op.attrs.get("in_num_col_dims", 1)) != 2:
        return None
    x_node = _input_node(fc, "Input")
    w_node = _input_node(fc, "W")
    b_fc = _input_node(fc, "Bias")
    if x_node is None or w_node is None or not w_node.persistable:
        return None
    return fc, proj, x_node, w_node, b_fc


def _rnn_struct_outs(g, keep_slots, protected):
    """Split ``g``'s outputs into the structural slots to keep vs the
    internal batch buffers, which must be dead for the fuse to be legal.
    Returns (outs dict, doomed list) or None."""
    outs, doomed = {}, []
    for v in g.outputs:
        slot = next((s for s in keep_slots
                     if g.op.output(s) and v.name in g.op.output(s)), None)
        if slot is not None:
            outs[slot] = v
        elif v.outputs or v.name in protected:
            return None
        else:
            doomed.append(v)
    if set(outs) != set(keep_slots):
        return None
    return outs, doomed


class _FCRNNFuseBase(Pass):
    """fc → {gru,lstm} ⇒ {fusion_gru,fusion_lstm} (ref ir/fc_gru_fuse_pass
    .cc, ir/fc_lstm_fuse_pass.cc).  Both RNN lowerings add Bias to the x
    pre-projection — the same pre-activation the fc bias lands on — so the
    fc bias folds numerically into the gate bias (needs ``scope=``)."""

    RNN = ""
    FUSED = ""
    OUTS = ()

    def apply_impl(self, graph: Graph) -> Graph:
        import numpy as np
        scope = self.get("scope")
        protected = self.protected_vars()
        count = 0
        for g in list(graph.ops_of_type(self.RNN)):
            if g not in graph.op_nodes:
                continue
            m = _match_fc_proj(g, protected)
            if m is None:
                continue
            fc, proj, x_node, w_node, b_fc = m
            bg_node = _input_node(g, "Bias")
            if b_fc is not None and bg_node is not None and scope is None:
                continue        # numeric bias fold needs param values
            so = _rnn_struct_outs(g, self.OUTS, protected)
            if so is None:
                continue        # a live internal batch buffer blocks it
            outs, dead_outs = so
            # fused gate bias = gru/lstm bias (+ fc bias over the gate
            # prefix — peephole tail, if any, is untouched)
            bias_nodes = None
            doomed_bias = []
            if b_fc is not None and bg_node is not None:
                bg = np.asarray(scope.find_var(bg_node.name), np.float64)
                bf = np.asarray(scope.find_var(b_fc.name),
                                np.float64).reshape(-1)
                fused = bg.copy()
                fused.reshape(-1)[:bf.size] += bf
                name = outs[self.OUTS[0]].name + ".fused_gate_bias"
                node = graph.create_var_node(
                    name, shape=tuple(bg.shape), dtype="float32",
                    persistable=True)
                scope.set_var(name, fused.astype(np.float32))
                bias_nodes = [node]
                doomed_bias = [
                    n for n in (b_fc, bg_node)
                    if all(c in (fc, g) for c in n.outputs) and
                    not _referenced_outside_block0(graph.program, n.name)]
                for n in doomed_bias:   # dead params must not stay
                    scope.erase(n.name)  # device-resident in serving
            elif b_fc is not None:
                bias_nodes = [b_fc]
            elif bg_node is not None:
                bias_nodes = [bg_node]
            inputs = {"X": [x_node], "WeightX": [w_node],
                      "WeightH": [_input_node(g, "Weight")]}
            if bias_nodes:
                inputs["Bias"] = bias_nodes
            for slot in ("H0", "C0", "SeqLen"):
                n = _input_node(g, slot)
                if n is not None:
                    inputs[slot] = [n]
            graph.create_op_node(
                self.FUSED, inputs=inputs,
                outputs={s: [outs[s]] for s in self.OUTS},
                attrs=dict(g.op.attrs))
            graph.safe_remove_nodes([fc, proj, g] + doomed_bias +
                                    dead_outs)
            count += 1
        graph.attrs[self.name.replace("_pass", "") + "_count"] = count
        return graph


@register_pass("fc_gru_fuse_pass")
class FCGRUFusePass(_FCRNNFuseBase):
    RNN, FUSED, OUTS = "gru", "fusion_gru", ("Hidden",)


@register_pass("fc_lstm_fuse_pass")
class FCLSTMFusePass(_FCRNNFuseBase):
    RNN, FUSED, OUTS = "lstm", "fusion_lstm", ("Hidden", "Cell")


@register_pass("embedding_fc_lstm_fuse_pass")
class EmbeddingFCLSTMFusePass(Pass):
    """lookup_table → fc → lstm ⇒ ``fused_embedding_fc_lstm`` with a
    pre-multiplied table (ref ir/embedding_fc_lstm_fuse_pass.cc): the new
    Embeddings value is emb·W_fc + b_fc per row, so the gate projection
    becomes a single row gather.  Needs ``scope=``; runs before
    fc_lstm_fuse_pass (more specific pattern first)."""

    def apply_impl(self, graph: Graph) -> Graph:
        import numpy as np
        scope = self.get("scope")
        if scope is None:
            raise ValueError("embedding_fc_lstm_fuse_pass needs scope= "
                             "to pre-multiply the embedding table")
        protected = self.protected_vars()
        count = 0
        for g in list(graph.ops_of_type("lstm")):
            if g not in graph.op_nodes:
                continue
            m = _match_fc_proj(g, protected)
            if m is None:
                continue
            fc, proj, emb_out, w_node, b_fc = m
            if emb_out.name in protected or len(emb_out.outputs) != 1:
                continue
            lt = None
            for t in ("lookup_table", "lookup_table_v2"):
                lt = lt or _sole_producer(emb_out, t)
            if lt is None:
                continue
            pad = lt.op.attrs.get("padding_idx", -1)
            if pad not in (-1, None):
                # a padding row embeds to zeros pre-projection; the
                # pre-multiplied table would bake b_fc into it — unsound
                continue
            emb_w = _input_node(lt, "W")
            ids = _input_node(lt, "Ids")
            if emb_w is None or not emb_w.persistable:
                continue
            if any(c is not lt for c in emb_w.outputs):
                continue        # shared table: other consumers keep it
            so = _rnn_struct_outs(g, ("Hidden", "Cell"), protected)
            if so is None:
                continue
            outs, dead_outs = so
            emb = np.asarray(scope.find_var(emb_w.name), np.float64)
            w = np.asarray(scope.find_var(w_node.name), np.float64)
            table = emb @ w
            if b_fc is not None:
                table = table + np.asarray(
                    scope.find_var(b_fc.name), np.float64).reshape(1, -1)
            name = outs["Hidden"].name + ".premul_embeddings"
            tbl_node = graph.create_var_node(
                name, shape=tuple(table.shape), dtype="float32",
                persistable=True)
            scope.set_var(name, table.astype(np.float32))
            inputs = {"Ids": [ids], "Embeddings": [tbl_node],
                      "WeightH": [_input_node(g, "Weight")]}
            bg = _input_node(g, "Bias")
            if bg is not None:
                inputs["Bias"] = [bg]
            for slot in ("H0", "C0", "SeqLen"):
                n = _input_node(g, slot)
                if n is not None:
                    inputs[slot] = [n]
            graph.create_op_node(
                "fused_embedding_fc_lstm", inputs=inputs,
                outputs={"Hidden": [outs["Hidden"]],
                         "Cell": [outs["Cell"]]},
                attrs=dict(g.op.attrs))
            doomed = [lt, emb_out, fc, proj, g] + dead_outs
            for n in (emb_w, w_node, b_fc):
                # consumed params leave graph AND scope — unless a
                # control-flow sub-block the Graph can't see shares them
                if n is not None and \
                        all(c in (lt, fc) for c in n.outputs) and \
                        not _referenced_outside_block0(graph.program,
                                                       n.name):
                    doomed.append(n)
                    scope.erase(n.name)  # don't keep the dead V×D table
            graph.safe_remove_nodes(doomed)
            count += 1
        graph.attrs["embedding_fc_lstm_fuse_count"] = count
        return graph


@register_pass("conv_elementwise_add_act_fuse_pass")
class ConvEltwiseAddActFusePass(Pass):
    """conv2d → elementwise_add(per-channel bias) → act ⇒ ``conv2d_fusion``
    (ref ir/conv_elementwise_add_act_fuse_pass.cc).  Must run before
    fuse_elewise_add_act_pass, which would otherwise consume the
    add→act tail."""

    ACTS = ("relu", "sigmoid", "tanh")

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for conv in list(graph.ops_of_type("conv2d")):
            if conv not in graph.op_nodes:
                continue
            conv_out = _output_node(conv, "Output")
            if conv_out is None or conv_out.name in protected or \
                    len(conv_out.outputs) != 1:
                continue
            add = conv_out.outputs[0]
            if not add.is_op("elementwise_add") or \
                    int(add.op.attrs.get("axis", -1)) != 1:
                continue
            bias = _input_node(add, "Y")
            if bias is None or not bias.persistable or \
                    bias.var is None or len(bias.var.shape or ()) != 1:
                continue
            add_out = _output_node(add, "Out")
            if add_out is None or add_out.name in protected or \
                    len(add_out.outputs) != 1:
                continue
            act = add_out.outputs[0]
            if not act.is_op() or act.name not in self.ACTS:
                continue
            out_node = act.outputs[0]
            attrs = dict(conv.op.attrs)
            attrs["activation"] = act.name
            graph.create_op_node(
                "conv2d_fusion",
                inputs={"Input": [_input_node(conv, "Input")],
                        "Filter": [_input_node(conv, "Filter")],
                        "Bias": [bias]},
                outputs={"Output": [out_node]}, attrs=attrs)
            graph.safe_remove_nodes([conv, conv_out, add, add_out, act])
            count += 1
        graph.attrs["conv_elementwise_add_act_fuse_count"] = count
        return graph


@register_pass("seqconv_eltadd_relu_fuse_pass")
class SeqConvEltAddReluFusePass(Pass):
    """sequence_conv → elementwise_add(bias) → relu ⇒
    ``fusion_seqconv_eltadd_relu`` (ref ir/seqconv_eltadd_relu_fuse_pass
    .cc — the text-CNN serving pattern)."""

    def apply_impl(self, graph: Graph) -> Graph:
        protected = self.protected_vars()
        count = 0
        for sc in list(graph.ops_of_type("sequence_conv")):
            if sc not in graph.op_nodes:
                continue
            if int(sc.op.attrs.get("contextStride", 1)) != 1:
                continue
            sc_out = _output_node(sc, "Out")
            if sc_out is None or sc_out.name in protected or \
                    len(sc_out.outputs) != 1:
                continue
            add = sc_out.outputs[0]
            if not add.is_op("elementwise_add"):
                continue
            bias = _input_node(add, "Y")
            if bias is None or not bias.persistable or \
                    bias.var is None or len(bias.var.shape or ()) != 1 or \
                    int(add.op.attrs.get("axis", -1)) != 2:
                continue        # only the 1-D per-filter feature bias
            add_out = _output_node(add, "Out")
            if add_out is None or add_out.name in protected or \
                    len(add_out.outputs) != 1:
                continue
            relu = add_out.outputs[0]
            if not relu.is_op("relu"):
                continue
            out_node = relu.outputs[0]
            graph.create_op_node(
                "fusion_seqconv_eltadd_relu",
                inputs={"X": [_input_node(sc, "X")],
                        "Filter": [_input_node(sc, "Filter")],
                        "Bias": [bias]},
                outputs={"Out": [out_node]},
                attrs={"contextLength":
                       sc.op.attrs.get("contextLength", 3),
                       "contextStart": sc.op.attrs.get("contextStart", 0)})
            graph.safe_remove_nodes([sc, sc_out, add, add_out, relu])
            count += 1
        graph.attrs["seqconv_eltadd_relu_fuse_count"] = count
        return graph


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """conv2d + batch_norm(is_test) → conv2d + folded weights
    (ref ir/conv_bn_fuse_pass.cc).  Numeric folding needs the param values:
    pass ``scope=`` when constructing.  W' = W·(γ/σ) per out-channel,
    b' = β − μ·γ/σ, emitted as an elementwise_add on the conv output (the
    reference does exactly this when conv has no bias)."""

    def apply_impl(self, graph: Graph) -> Graph:
        import numpy as np
        scope = self.get("scope")
        if scope is None:
            raise ValueError("conv_bn_fuse_pass needs scope= with param "
                             "values to fold numerically")
        count = 0
        for bn in list(graph.ops_of_type("batch_norm")):
            if bn not in graph.op_nodes:
                continue
            if not bn.op.attrs.get("is_test") and \
                    not bn.op.attrs.get("use_global_stats"):
                continue
            conv_out = next((v for v in bn.inputs
                             if v.inputs and v.inputs[0].is_op("conv2d")),
                            None)
            if conv_out is None or len(conv_out.outputs) != 1:
                continue
            conv = conv_out.inputs[0]
            w_shared = next((v for v in conv.inputs if v.persistable), None)
            if w_shared is None:
                # filter is not a plain persistable weight (e.g. a QAT
                # .quantized intermediate) — nothing to fold numerically
                continue
            if any(c is not conv for c in w_shared.outputs):
                # folding mutates the filter values in the scope — a shared
                # filter would silently corrupt its other consumers
                continue
            by_name = {v.name: v for v in bn.inputs}
            op = bn.op
            scale_n = op.input("Scale")[0]
            bias_n = op.input("Bias")[0]
            mean_n = op.input("Mean")[0]
            var_n = op.input("Variance")[0]
            w_node = next(v for v in conv.inputs if v.persistable)
            eps = op.attrs.get("epsilon", 1e-5)
            gamma = np.asarray(scope.find_var(scale_n), np.float64)
            beta = np.asarray(scope.find_var(bias_n), np.float64)
            mu = np.asarray(scope.find_var(mean_n), np.float64)
            var = np.asarray(scope.find_var(var_n), np.float64)
            w = np.asarray(scope.find_var(w_node.name), np.float64)
            factor = gamma / np.sqrt(var + eps)       # [out_c]
            scope.set_var(w_node.name,
                          (w * factor.reshape(-1, 1, 1, 1)).astype(
                              np.float32))
            fused_bias_name = bn.op.output("Y")[0] + ".conv_bn_bias"
            bias_node = graph.create_var_node(
                fused_bias_name, shape=(len(factor),), dtype="float32",
                persistable=True)
            scope.set_var(fused_bias_name,
                          (beta - mu * factor).astype(np.float32))
            y_node = next(v for v in bn.outputs
                          if v.name in op.output("Y"))
            graph.create_op_node(
                "elementwise_add",
                inputs={"X": [conv_out], "Y": [bias_node]},
                outputs={"Out": [y_node]},
                attrs={"axis": 1})
            # stat outputs (MeanOut etc.) die with the bn node
            doomed = [bn] + [v for v in bn.outputs if v is not y_node]
            doomed += [by_name[n] for n in
                       (scale_n, bias_n, mean_n, var_n)
                       if n in by_name and
                       all(c is bn for c in by_name[n].outputs)]
            graph.safe_remove_nodes(doomed)
            count += 1
        graph.attrs["conv_bn_fuse_count"] = count
        return graph


# ---------------------------------------------------------------------------
# Memory-analysis passes (ref ir/memory_optimize_pass/)
# ---------------------------------------------------------------------------

@register_pass("reference_count_pass")
class ReferenceCountPass(Pass):
    """Liveness: last-use op index per non-persistable var
    (ref reference_count_pass.cc).  Under the block-compiler XLA frees
    temporaries itself; this analysis feeds donation and debugging
    (``graph.attrs['last_use']``)."""

    def apply_impl(self, graph: Graph) -> Graph:
        order = {op.id: i for i, op in enumerate(graph.topology_sort())}
        last_use: Dict[str, int] = {}
        for v in graph.all_var_nodes():
            if v.persistable:
                continue
            uses = [order[c.id] for c in v.outputs if c.id in order]
            if uses:
                last_use[v.name] = max(uses)
        graph.attrs["last_use"] = last_use
        return graph


@register_pass("buffer_shared_inplace_pass")
class BufferSharedInplacePass(Pass):
    """Pairs (in, out) an op could compute in place because the input dies
    there (ref buffer_shared_inplace_op_pass.cc).  XLA's buffer assigner
    performs the actual aliasing; the pairs inform ``donate_argnums`` for
    feed buffers (``graph.attrs['inplace_pairs']``)."""

    INPLACE_OPS = ("relu", "scale", "reshape", "reshape2", "squeeze",
                   "squeeze2", "unsqueeze", "unsqueeze2", "flatten",
                   "flatten2", "elementwise_add", "softmax", "dropout")

    def apply_impl(self, graph: Graph) -> Graph:
        graph = get_pass("reference_count_pass").apply(graph)
        last_use = graph.attrs["last_use"]
        order = {op.id: i for i, op in enumerate(graph.topology_sort())}
        pairs = []
        for op in graph.all_op_nodes():
            if op.name not in self.INPLACE_OPS:
                continue
            for vin in op.inputs:
                if vin.persistable or vin.name not in last_use:
                    continue
                if last_use[vin.name] == order[op.id] and op.outputs:
                    pairs.append((vin.name, op.outputs[0].name))
                    break
        graph.attrs["inplace_pairs"] = pairs
        return graph


#: op types executed for their effect, not their outputs: always liveness
#: roots (ref the reference's GC whitelist in eager_deletion_pass.cc —
#: ops a liveness sweep must never collect)
SIDE_EFFECT_OPS = frozenset({
    "feed", "fetch", "listen_and_serv", "send", "recv", "print", "assert",
    "save", "load", "py_func", "gen_nccl_id",
})


def dead_op_analysis(graph: Graph, protected=frozenset()) -> List[Node]:
    """Liveness from fetch + persistable + side-effect roots: the op nodes
    whose outputs reach none of them (the verifier's ``dead_op`` check and
    the ``dead_op_eliminate`` pass share this sweep).

    Roots (deliberately conservative — a falsely-dead op silently corrupts
    results, a falsely-live op only wastes XLA's own DCE a few ns):
    - ops writing a ``protected`` (fetched) var or any persistable,
    - ops writing a var any control-flow SUB-block references (the block-0
      graph cannot see those consumers),
    - side-effecting op types (:data:`SIDE_EFFECT_OPS`, every ``c_*``
      collective, and any op carrying a Block-valued attr — its sub-block
      may write persistables),
    - ops with no outputs at all.
    Everything reaching a root through data dependencies is live; the rest
    is dead."""
    from .core import Block as _Block
    program = graph.program
    block = program.blocks[graph.block_idx]
    sub_refs = set()
    for blk in program.blocks:
        if blk.idx == graph.block_idx:
            continue
        for op in blk.ops:
            sub_refs.update(op.input_arg_names())
            sub_refs.update(op.output_arg_names())

    def persistable(name):
        return block.has_var(name) and block.var(name).persistable

    def is_root(op_node: Node) -> bool:
        op = op_node.op
        if op.type in SIDE_EFFECT_OPS or op.type.startswith("c_"):
            return True
        if any(isinstance(v, _Block) for v in op.attrs.values()):
            return True
        outs = [n for n in op.output_arg_names() if n]
        if not outs:
            return True
        return any(n in protected or n in sub_refs or persistable(n)
                   for n in outs)

    live = {n.id for n in graph.op_nodes if is_root(n)}
    stack = [n for n in graph.op_nodes if n.id in live]
    while stack:
        op_node = stack.pop()
        for v in op_node.inputs:
            for producer in v.inputs:
                if producer.id not in live:
                    live.add(producer.id)
                    stack.append(producer)
    return [n for n in graph.op_nodes if n.id not in live]


def dead_subblock_op_analysis(program: Program,
                              protected=frozenset()) -> Dict[int, tuple]:
    """Per-sub-block liveness: for every block idx > 0, the program-order
    op indices whose outputs reach none of the block's liveness roots —
    the sub-block counterpart of :func:`dead_op_analysis`, with the roots
    adjusted for loop semantics (live loop-carried vars must survive):

    - ops writing a name ANY other block references (carried vars and
      the condition appear in the enclosing ``while``/``cond`` op's
      input/output lists, so their writers are roots; so are writers of
      vars a nested body reads),
    - ops writing a ``protected`` (fetched) name or any persistable,
    - side-effecting op types, every ``c_*`` collective, ops carrying a
      nested Block attr, and ops with no outputs.

    Everything reaching a root through the block's own def-use chains is
    live; the rest is dead body compute nothing observes (its outputs
    feed no carry, no fetch, no persistable — it burns trace time and
    loop FLOPs every iteration).  Returns {block_idx: (op indices...)}
    for blocks with at least one dead op."""
    from .core import Block as _Block
    out: Dict[int, tuple] = {}
    for block in program.blocks[1:]:
        # names referenced by ANY op outside this block (enclosing
        # control-flow ops list carried vars / Condition / Out there)
        ext_refs = set()
        for other in program.blocks:
            if other.idx == block.idx:
                continue
            for op in other.ops:
                ext_refs.update(op.input_arg_names())
                ext_refs.update(op.output_arg_names())
                for v in op.attrs.values():
                    if isinstance(v, _Block) and v.idx == block.idx:
                        # the enclosing op's attr lists (carried_vars,
                        # cond_var, state_vars...) reference body names
                        # without appearing in its input/output slots
                        for av in op.attrs.values():
                            if isinstance(av, (list, tuple)):
                                ext_refs.update(
                                    x for x in av if isinstance(x, str))
                            elif isinstance(av, str):
                                ext_refs.add(av)

        def persistable(name, _b=block):
            return _b.has_var(name) and _b.var(name).persistable

        def is_root(op) -> bool:
            if op.type in SIDE_EFFECT_OPS or op.type.startswith("c_"):
                return True
            if any(isinstance(v, _Block) for v in op.attrs.values()):
                return True
            outs = [n for n in op.output_arg_names() if n]
            if not outs:
                return True
            return any(n in protected or n in ext_refs or persistable(n)
                       for n in outs)

        live = {i for i, op in enumerate(block.ops) if is_root(op)}
        # backward closure over the block's own def-use: any op writing
        # a name a live op reads is live (conservative on rewrites)
        changed = True
        while changed:
            changed = False
            needed = {n for i in live
                      for n in block.ops[i].input_arg_names() if n}
            for i, op in enumerate(block.ops):
                if i in live:
                    continue
                if needed & {n for n in op.output_arg_names() if n}:
                    live.add(i)
                    changed = True
        dead = tuple(i for i in range(len(block.ops)) if i not in live)
        if dead:
            out[block.idx] = dead
    return out


def prune_subblock_ops(program: Program,
                       dead_map: Dict[int, tuple]) -> int:
    """Drop the ops named by :func:`dead_subblock_op_analysis` from
    ``program``'s sub-blocks (in place).  Returns the removal count."""
    removed = 0
    for idx, indices in (dead_map or {}).items():
        if idx <= 0 or idx >= len(program.blocks):
            continue
        block = program.blocks[idx]
        doomed = set(indices)
        kept = [op for i, op in enumerate(block.ops) if i not in doomed]
        removed += len(block.ops) - len(kept)
        block.ops = kept
    if removed:
        program._bump_version()
    return removed


@register_pass("dead_op_eliminate")
class DeadOpEliminatePass(Pass):
    """Remove ops unreachable from the fetch/persistable/side-effect
    liveness roots (:func:`dead_op_analysis`).  Under XLA the compiler
    DCEs the lowered computation anyway — the win is never TRACING the
    dead subgraph (a dead attention head still costs its full trace +
    shape inference time) and keeping donation/liveness analyses honest.
    ``protected`` names the fetch targets, same contract as the fusion
    passes; removal count lands in
    ``graph.attrs['dead_op_eliminate_count']``.

    Sub-blocks too: dead compute inside ``while``/``cond`` bodies
    (:func:`dead_subblock_op_analysis` — live loop-carried vars always
    survive) is recorded in ``graph.attrs['dead_subblock_ops']`` and
    pruned when the graph materializes via :meth:`Graph.to_program` /
    :meth:`Graph.apply_to_program`; the count adds into
    ``dead_op_eliminate_count``."""

    def apply_impl(self, graph: Graph) -> Graph:
        dead = dead_op_analysis(graph, self.protected_vars())
        # every consumer of a dead op's output is itself dead (liveness is
        # a backward closure), so the output var nodes go with their ops
        doomed_vars = [v for n in dead for v in n.outputs]
        graph.safe_remove_nodes(list(dead) + doomed_vars)
        sub_dead = dead_subblock_op_analysis(graph.program,
                                             self.protected_vars())
        graph.attrs["dead_subblock_ops"] = sub_dead
        graph.attrs["dead_op_eliminate_count"] = \
            len(dead) + sum(len(v) for v in sub_dead.values())
        return graph


# ---------------------------------------------------------------------------
# Graph viz / round-trip passes
# ---------------------------------------------------------------------------

@register_pass("graph_viz_pass")
class GraphVizPass(Pass):
    """DOT dump (ref ir/graph_viz_pass.cc).  ``graph_viz_path`` attr writes
    to a file; the DOT text is also returned in
    ``graph.attrs['graph_viz_dot']``."""

    def apply_impl(self, graph: Graph) -> Graph:
        lines = ["digraph G {", "  rankdir=TB;"]
        for op in graph.all_op_nodes():
            lines.append(
                f'  n{op.id} [label="{op.name}" shape=box '
                f'style=filled fillcolor="#ffd39b"];')
        highlights = frozenset(self.get("highlights") or ())
        for v in graph.all_var_nodes():
            shape = "ellipse"
            fill = "#f4adad" if v.name in highlights else \
                "#c0d9ee" if not v.persistable else "#b5e7b5"
            lines.append(
                f'  n{v.id} [label="{v.name}" shape={shape} '
                f'style=filled fillcolor="{fill}"];')
        for n in graph.all_op_nodes() + graph.all_var_nodes():
            for o in n.outputs:
                lines.append(f"  n{n.id} -> n{o.id};")
        lines.append("}")
        dot = "\n".join(lines)
        graph.attrs["graph_viz_dot"] = dot
        path = self.get("graph_viz_path")
        if path:
            with open(path, "w") as f:
                f.write(dot)
        return graph


@register_pass("graph_to_program_pass")
class GraphToProgramPass(Pass):
    """Round-trip Graph → ProgramDesc (ref ir/graph_to_program_pass.cc);
    result in ``graph.attrs['program']``."""

    def apply_impl(self, graph: Graph) -> Graph:
        graph.attrs["program"] = graph.to_program()
        return graph
