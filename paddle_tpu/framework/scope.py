"""Scope: name → value store for persistent variables.

Mirrors the reference's hierarchical ``Scope`` (``framework/scope.h:46``):
a name→Variable map with parent fallback.  Values here are JAX Arrays living
on device (or host numpy before first device_put); temporaries never enter a
Scope — they are SSA values inside the lowered XLA computation, which is the
TPU-native equivalent of the reference's local-scope + eager-deletion GC
(``framework/executor.cc:106-141``, ``garbage_collector.h``): XLA's buffer
liveness analysis does that job during compilation.
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Any, Dict, Iterator, Optional

#: monotonic scope identity tokens — the executor's compiled-block cache
#: keys on ``scope._serial`` rather than ``id(scope)``: after GC, a new
#: scope can reuse a dead scope's id and silently hit an entry whose
#: persistable classification was computed against the dead scope.
_scope_serials = itertools.count()


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self._vars: Dict[str, Any] = {}
        self.kids = []
        self._serial = next(_scope_serials)
        # device-resident scope epoch (async write-back plane): bumped
        # once per batch write-back (executor step boundary).  Values
        # written by a step are in-flight jax Arrays — find_var stays
        # LAZY on them (no host sync); a host consumer that needs bytes
        # calls materialize().  The epoch lets such consumers (and the
        # pjit reshard path) detect "scope advanced since I last read"
        # with one int compare instead of touching device buffers.
        self.epoch = 0

    def var(self, name: str):
        """Create-or-get, like ref Scope::Var."""
        if name not in self._vars:
            self._vars[name] = None
        return self._vars.get(name)

    def _owning_scope(self, name: str) -> Optional["Scope"]:
        """Nearest scope (self → ancestors) whose dict holds ``name``."""
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s
            s = s.parent
        return None

    def find_var(self, name: str):
        s = self._owning_scope(name)
        return s._vars[name] if s is not None else None

    def has_var(self, name: str) -> bool:
        return self._owning_scope(name) is not None

    def set_var(self, name: str, value) -> None:
        self._vars[name] = value

    def set_vars(self, mapping: Dict[str, Any]) -> None:
        """Batch write-back of one step's updated persistables: a single
        dict.update + ONE epoch bump, so every var of a step lands under
        the same epoch (the executor's _finish_run path — per-name
        set_var loops would publish a torn epoch where a concurrent
        reader sees step N's moments next to step N-1's params)."""
        self._vars.update(mapping)
        self.epoch += 1

    def materialize(self, name: str):
        """Host-materialize one var: resolve ``name`` (parent fallback),
        block until the device buffer is ready, store and return the
        host copy.  The boundary where the async write-back plane's
        laziness ends — checkpoint writers and eval readers that need
        bytes call this instead of np.asarray(find_var(...)) so the
        sync is attributed here, not hidden inside a numpy coercion."""
        s = self._owning_scope(name)
        if s is None:
            return None
        v = s._vars[name]
        if hasattr(v, "block_until_ready"):
            v.block_until_ready()
        return v

    def erase(self, name: str) -> None:
        """Remove ``name`` from the scope that OWNS it (same walk as
        ``find_var``): callers erase dead params after IR fusion, and a
        param found through a child scope would otherwise stay resident
        in the parent — silently defeating the erase."""
        s = self._owning_scope(name)
        if s is not None:
            del s._vars[name]

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def drop_kids(self) -> None:
        self.kids.clear()

    def local_var_names(self) -> Iterator[str]:
        return iter(list(self._vars))

    def items(self):
        return self._vars.items()


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    """ref ``python/paddle/fluid/executor.py`` scope_guard."""
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()
