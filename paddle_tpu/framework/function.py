"""Program → pure-function export: lower a Program block to a callable
``fn(params_dict, *feeds) -> fetches`` suitable for jax.jit / AOT export.

This is the functional face of the Executor's block compiler — used by
``__graft_entry__``, the inference engine, and anywhere a Program must
compose with raw JAX transforms.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax

from .core import Program, Variable
from .executor import Executor, LowerCtx, _ExecState, run_block
from .scope import Scope, scope_guard


def init_program_params(startup_program: Program, scope=None, seed=0):
    """Run a startup program, returning {name: jax.Array} of persistables."""
    scope = scope or Scope()
    with scope_guard(scope):
        exe = Executor()
        exe.run(startup_program, seed=seed)
    return {name: val for name, val in scope.items() if val is not None}


def program_as_function(program: Program, feed_names: Sequence[str],
                        fetch_names: Sequence[str]):
    """Return fn(params, *feeds) -> tuple(fetches); params is {name: array}
    of every persistable the block reads."""
    block = program.global_block()
    feed_names = [f.name if isinstance(f, Variable) else f for f in feed_names]
    fetch_names = [f.name if isinstance(f, Variable) else f
                   for f in fetch_names]

    def fn(params: Dict[str, jax.Array], *feeds):
        values = dict(params)
        values.update(zip(feed_names, feeds))
        state = _ExecState(values)
        run_block(LowerCtx(jax.random.key(0)), block, state)
        return tuple(state.values[n] for n in fetch_names)

    return fn


def program_as_train_step(program: Program, feed_names: Sequence[str],
                          fetch_names: Sequence[str],
                          state_names: Sequence[str]):
    """fn(state, *feeds) -> (fetches, new_state): one full optimizer step as
    a pure function over the training state (params + accumulators)."""
    block = program.global_block()
    feed_names = [f.name if isinstance(f, Variable) else f for f in feed_names]
    fetch_names = [f.name if isinstance(f, Variable) else f
                   for f in fetch_names]

    def fn(state: Dict[str, jax.Array], *feeds, seed=0):
        values = dict(state)
        values.update(zip(feed_names, feeds))
        st = _ExecState(values)
        run_block(LowerCtx(jax.random.key(seed)), block, st)
        fetches = tuple(st.values[n] for n in fetch_names)
        new_state = {n: st.values[n] for n in state_names}
        return fetches, new_state

    return fn
