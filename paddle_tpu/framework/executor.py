"""Executor: lowers a whole Program block to ONE jitted XLA computation.

The reference Executor (``framework/executor.cc:173,398-440``) interprets a
block op-by-op, dispatching a C++/CUDA kernel per op and garbage-collecting
dead tensors between ops.  On TPU that per-op dispatch is precisely what you
must NOT do — so this Executor plays the role the reference's nGraph subgraph
engine prototyped (``operators/ngraph/ngraph_engine.cc:249-531``: capture
block → build function → shape-keyed compiled-function cache): the *entire*
block becomes one traced JAX function, jit-compiled by XLA, cached by
(program fingerprint, feed shapes/dtypes, fetch set).

Step signature of the lowered function::

    step(feeds, persist_ro, persist_rw, seed) -> (fetches, new_persist_rw)

``persist_rw`` (params + optimizer state + BN running stats — anything a
block op writes) is donated to XLA so parameter updates alias their input
buffers, matching the reference's in-place optimizer kernels without any
explicit memory pass (ref ``ir/memory_optimize_pass/``— XLA buffer
assignment subsumes it).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import registry
from .. import monitor as _monitor
from .. import resilience as _resil
from .core import Block, Operator, Program, Variable, default_main_program
from .scope import Scope, global_scope

#: executor-wide telemetry families (paddle_tpu.monitor.REGISTRY): the
#: dispatch counters below are per-executor label series of these same
#: families, so `Executor.dispatch_stats()`, the profiler aggregate, and
#: the JSON/Prometheus exporters read ONE store
_THROTTLE_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_executor_throttle_wait_us",
    "in-flight throttle: host wait per blocking probe pop (us)")
_COMPILE_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_compile_ms",
    "trace + lower + XLA compile wall time per fresh compiled block (ms)",
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 120000.0))
_COMPILE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_compile_total",
    "fresh compiled blocks by persistent-cache outcome: 'write' = new "
    "disk-cache entry persisted, 'hit' = cache dir set and no write "
    "(disk hit, or compile under the persist threshold), 'off' = "
    "FLAGS_xla_compile_cache_dir unset", ("persist",))
_COLLECTIVE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_collective_launches_total",
    "host-launched collectives by kind (in-graph c_* ops are compiled "
    "into the step and do not count here)", ("kind",))
#: runtime device-time attribution (analysis.cost): live MFU as a
#: per-executor gauge series instead of a bench-only offline number.
#: step_device_ms is the windowed median inter-dispatch interval — in a
#: throttled steady-state loop the host dispatches exactly as fast as
#: the device retires steps, so the interval IS the per-step device
#: time; mfu = analytic flops/step over (interval x chip peak).
_STEP_MS_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_device_ms",
    "median per-step time (ms) at the dispatch boundary — equals "
    "device step time in a throttled steady-state loop", ("executor",))
_STEP_MFU_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_mfu",
    "live model-flops utilization in [0,1]: analytic flops/step "
    "(analysis.cost) over step-time estimate x device peak", ("executor",))
_CLASS_SHARE_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_flops_share",
    "analytic flop share by op class of the most recently planned "
    "step (conv/matmul/embedding/norm/softmax/attention/...) — the "
    "roofline attribution the fusion arc picks candidates from",
    ("op_class",))
_ANALYTIC_FLOPS_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_analytic_step_flops",
    "analytic flops per step of the most recently compiled block")
_XLA_FLOPS_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_xla_step_flops",
    "XLA cost_analysis() flops per step of the most recently "
    "cross-checked block (FLAGS_cost_crosscheck)")
_COST_XCHK_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_cost_crosscheck_total",
    "analytic-cost vs compiled.cost_analysis() comparisons at compile "
    "time: 'ok' within the 3x band, 'divergent' outside it, 'skipped' "
    "for programs without dominant MXU-class work, 'unavailable' when "
    "XLA reported no flops", ("verdict",))
_COST_XCHK_CLASS_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_cost_crosscheck_divergent_total",
    "divergent cost crosschecks attributed to the analytic op class "
    "with the largest flop share — the class whose formula to audit "
    "first", ("op_class",))
#: analytic-vs-XLA agreement band: XLA folds elementwise work into
#: fusions and counts transcendentals its own way, so exact equality is
#: not expected — an order-of-magnitude drift is what the gate catches
_COST_XCHK_BAND = 3.0

_HELP = {
    "cache_hits": "dispatches served by the compiled-block cache",
    "cache_misses": "dispatches that missed the compiled-block cache",
    "traces": "full block re-lowerings (trace + jit)",
    "steps_dispatched": "steps handed to the device",
    "lazy_fetch_steps": "steps returning in-flight FetchHandles",
    "eager_fetch_steps": "steps materializing fetches before returning",
    "fetch_materializations": "device->host fetch syncs",
    "throttle_waits": "blocking pops of the in-flight throttle",
    "time_to_dispatch_us": "host us from run() entry to async-dispatch "
                           "return",
    "host_block_us": "total host-blocked-on-device us (all causes)",
    "materialize_block_us": "host-blocked us in fetch materialization",
    "throttle_block_us": "host-blocked us in the in-flight throttle",
    "benchmark_sync_us": "host-blocked us in FLAGS_benchmark per-step "
                         "syncs",
}

_stats_serials = itertools.count()


class _DispatchStats:
    """Per-executor dispatch counters — the per-step 'framework tax' ledger.

    Everything the host does per ``run()`` that is NOT the XLA step itself
    shows up here: cache lookups (hit/miss), re-lowerings (``traces``), the
    host time from ``run()`` entry to async dispatch return
    (``time_to_dispatch_us``), and every point where the host BLOCKS on the
    device (``host_block_us``, split by cause: fetch materialization,
    in-flight throttle, FLAGS_benchmark per-step sync).  A healthy
    steady-state loop with lazy fetches shows hits ≥ steps, zero traces,
    and host-block time concentrated at materialization boundaries.

    Storage is the monitor metrics registry: each field is the
    ``executor=<serial>`` label series of a process-wide counter family,
    bound once here so a bump stays one lock + add (counters are hit from
    concurrent run() threads AND FetchHandle.numpy() consumer threads —
    a bare ``+=`` would lose updates under contention).  Because the
    registry is the single store, a metrics export matches
    ``dispatch_stats()`` by construction.
    """

    _INT_FIELDS = ("cache_hits", "cache_misses", "traces",
                   "steps_dispatched", "lazy_fetch_steps",
                   "eager_fetch_steps", "fetch_materializations",
                   "throttle_waits")
    _US_FIELDS = ("time_to_dispatch_us", "host_block_us",
                  "materialize_block_us", "throttle_block_us",
                  "benchmark_sync_us")

    def __init__(self):
        self.serial = next(_stats_serials)
        lbl = {"executor": str(self.serial)}
        self._fams = {
            f: _monitor.REGISTRY.counter(
                "paddle_tpu_executor_" + f, _HELP[f], ("executor",))
            for f in self._INT_FIELDS + self._US_FIELDS}
        self._cells = {f: fam.labels(**lbl)
                       for f, fam in self._fams.items()}
        # live attribution gauges, bound once (a per-step update is two
        # lock+store ops — the hot path never resolves labels)
        self._ms_cell = _STEP_MS_GAUGE.labels(**lbl)
        self._mfu_cell = _STEP_MFU_GAUGE.labels(**lbl)

    def set_step_timing(self, step_ms: float, mfu: float):
        self._ms_cell.set(step_ms)
        self._mfu_cell.set(mfu)

    def retire(self):
        """Fold this executor's label series into ``executor="retired"``
        and drop them: a fresh-executor-per-request loop must not grow
        the registry one series set per executor, while process-lifetime
        totals (``monitor.counter_totals()``) stay exact.  Called from a
        GC finalizer on the owning executor.  The live cells are then
        REBOUND to the retired series: a FetchHandle outliving its
        executor still bumps fetch_materializations through this stats
        object, and a detached cell would silently drop those counts."""
        src = {"executor": str(self.serial)}
        dst = {"executor": "retired"}
        retired = {f: fam.labels(**dst) for f, fam in self._fams.items()}
        for fam in self._fams.values():
            fam.fold(src, dst)
        self._cells = retired
        # a dead executor's last step time / MFU is meaningless: drop
        # the gauge series (PR-2 retirement semantics for gauges); the
        # detached cells absorb any straggling set() harmlessly
        _STEP_MS_GAUGE.fold(src, None)
        _STEP_MFU_GAUGE.fold(src, None)

    def reset(self):
        for c in self._cells.values():
            c.reset()

    def incr(self, field: str, n=1):
        self._cells[field].inc(n)

    def block(self, cause_field: str, dt_us: float):
        """Record ``dt_us`` of host-blocked time attributed to a cause."""
        self._cells[cause_field].inc(dt_us)
        self._cells["host_block_us"].inc(dt_us)

    def snapshot(self) -> Dict[str, Any]:
        out = {f: int(self._cells[f].get()) for f in self._INT_FIELDS}
        out.update({f: float(self._cells[f].get())
                    for f in self._US_FIELDS})
        return out


#: host-launched collective kinds, bound once (hot-path bumps are then a
#: lock + add, no label resolution)
_COLL_STEP = _COLLECTIVE_CTR.labels(kind="shard_map_step")
_COLL_ALLGATHER = _COLLECTIVE_CTR.labels(kind="process_allgather")
_COLL_H2G = _COLLECTIVE_CTR.labels(kind="host_to_global")
_COLL_BARRIER = _COLLECTIVE_CTR.labels(kind="step_barrier")


def _compile_cache_entries(cache_dir: str) -> int:
    """File count under the persistent XLA compile cache dir (hit/miss
    heuristic for compile telemetry; '' → cache off → -1)."""
    if not cache_dir:
        return -1
    try:
        return sum(len(files) for _, _, files in os.walk(cache_dir))
    except OSError:
        return -1


#: live executors, for profiler-level aggregation (weak: an executor's
#: stats die with it, matching the reference's per-executor profiler state)
_EXECUTORS: "weakref.WeakSet" = weakref.WeakSet()

#: process-global step ids: every dispatch (any executor) gets one, and
#: the SAME id keys the host-side executor.dispatch tracer span, the
#: jax.profiler StepTraceAnnotation the device trace records, and the
#: sampling-profiler window manifest — so a device trace window maps
#: back to exactly the monitor.py spans it overlapped
_GLOBAL_STEPS = itertools.count(1)

#: the most recently ISSUED step id (0 before the first dispatch).  A
#: plain int store under the GIL; readers (the serving scheduler
#: stamping its serving.dispatch span so a request trace joins the
#: device trace) get *a* recent step id — with concurrent executors
#: that is exactly the precision a correlation hint can honestly offer.
_LAST_STEP_ID = 0


def last_step_id() -> int:
    """Process-global id of the most recently dispatched step (the same
    id on the executor.dispatch span and the StepTraceAnnotation)."""
    return _LAST_STEP_ID


_device_peak_cache: List[float] = []


def _maybe_sample_step(step_id: int, step_ms=None) -> None:
    """Memoized trampoline to profiler.maybe_sample_step: the profiler
    module cannot be imported at executor module load (it resolves
    through the partially-initialized package during bootstrap), and a
    per-dispatch import statement would put import-lock machinery on
    the hottest path — so the bound function is cached on first use.
    ``step_ms`` (the windowed median dispatch interval) feeds the
    FLAGS_profile_sample_regress_frac auto-trigger."""
    global _maybe_sample_step
    from ..profiler import maybe_sample_step
    _maybe_sample_step = maybe_sample_step
    maybe_sample_step(step_id, step_ms)


_fusion_mod = []


def _fusion():
    """Memoized analysis.fusion module (same bootstrap rationale as the
    sampler trampoline — the hot path reads one config token per run)."""
    if not _fusion_mod:
        from ..analysis import fusion
        _fusion_mod.append(fusion)
    return _fusion_mod[0]


_numerics_mod = []


def _numerics():
    """Memoized analysis.numerics module (the hot path reads one mode
    string per run; the engine consumes the lazily-fetched stats)."""
    if not _numerics_mod:
        from ..analysis import numerics
        _numerics_mod.append(numerics)
    return _numerics_mod[0]


_comms_mod = []


def _comms():
    """Memoized analysis.comms module (same bootstrap rationale as the
    trampolines above; the collective launch path reads it per dispatch)."""
    if not _comms_mod:
        from ..analysis import comms
        _comms_mod.append(comms)
    return _comms_mod[0]


_hbm_mod = []


def _hbm():
    """Memoized paddle_tpu.hbm module (the step boundary reads one
    enabled flag + queues one record per sampled step)."""
    if not _hbm_mod:
        from .. import hbm
        _hbm_mod.append(hbm)
    return _hbm_mod[0]


def _device_peak() -> float:
    """Memoized chip peak FLOP/s (the live-MFU denominator)."""
    if not _device_peak_cache:
        from ..analysis.cost import device_peak_flops
        _device_peak_cache.append(device_peak_flops())
    return _device_peak_cache[0]


def _restamp_memory(program, fetch_names, batch):
    """PR-7 follow-on: the verifier's HBM plan is a batch=1 lower bound
    stamped before any dispatch plan exists; once the executor knows the
    REAL feed shapes, re-plan at that batch and re-stamp
    ``_attrs["verify"]["memory"]`` so tools/bench/OOM reports see the
    actual step footprint (fingerprint-cached — a one-off per block)."""
    va = program._attrs.get("verify")
    if va is None or batch <= 1:
        return
    from ..analysis.memory import plan_memory
    plan = plan_memory(program, fetch_names, batch_size=batch)
    va["memory"] = {
        "peak_bytes": plan.peak_bytes,
        "resident_bytes": plan.resident_bytes,
        "steady_bytes": plan.steady_bytes,
        "peak_op": plan.peak_op,
        "top_ops": [(p, t, b) for p, t, b, _ in plan.top_ops(5)],
        "batch": batch,
    }


def _resolve_hbm_info(cb, program, feeds):
    """Once per compiled block: the class name-sets (params vs other
    persistables = optimizer state / BN stats) plus the static plan's
    bytes at the real batch — what the off-thread HBM accountant joins
    live samples against.  Prefers the ``_attrs["verify"]["memory"]``
    stamp ``_resolve_cost`` re-planned earlier in the same first
    dispatch; programs the verifier never stamped plan directly
    (``plan_memory`` is fingerprint-cached, so this is a one-off per
    block, the same cost the restamp pays).  None on failure —
    accounting must never break dispatch."""
    try:
        block = program.global_block()
        params, opt = [], []
        for n in tuple(cb.persist_ro) + tuple(cb.persist_rw):
            if not block.has_var(n):
                continue
            v = block.var(n)
            if not v.persistable:
                continue
            (params if getattr(v, "is_parameter", False)
             else opt).append(n)
        va = program._attrs.get("verify") or {}
        mem = va.get("memory") or {}
        steady = int(mem.get("steady_bytes", 0) or 0)
        peak = int(mem.get("peak_bytes", 0) or 0)
        batch = int(mem.get("batch", 1) or 1)
        if not steady:
            from ..analysis.memory import plan_memory
            batch = _feed_batch(feeds)
            plan = plan_memory(program, cb.fetch_names,
                               batch_size=batch)
            steady, peak = int(plan.steady_bytes), int(plan.peak_bytes)
        return {"params": frozenset(params), "opt_state": frozenset(opt),
                "plan_steady": steady, "plan_peak": peak,
                "plan_batch": batch}
    except Exception:
        return None


def _feed_batch(feeds) -> int:
    """Batch size of a staged feed list: the leading dim of the first
    shaped feed (the convention every planner resolves -1 dims
    through); 1 when nothing is shaped.  Shared by the cost and comms
    resolvers so the two plans can never price different batches for
    the same block."""
    for f in feeds:
        shape = getattr(f, "shape", None)
        if shape:
            return int(shape[0])
    return 1


def _resolve_comms(cb, program, feeds):
    """Once per compiled collective block: the static comms plan at the
    REAL feed batch plus the pre-bound per-collective byte-counter cells
    (analysis.comms) — the per-dispatch accounting is then a lock+add per
    collective.  Returns (plan, [(cell, payload_bytes)]) or None; comms
    modeling must never break dispatch."""
    try:
        comms = _comms()
        plan = comms.plan_comms(program, cb.fetch_names,
                                batch_size=_feed_batch(feeds),
                                nranks=cb.collective_nranks)
        if plan is None and getattr(cb, "partitioned", False):
            # pjit-partitioned programs launch no explicit c_* ops for
            # plan_comms to find — their collective traffic is the
            # GSPMD reshard plan (analysis.sharding), projected onto
            # the same CommsPlan shape so the byte cells, wait/wire
            # decomposition, and gangtop COMM column work unchanged
            from ..analysis import sharding as _sharding
            plan = _sharding.runtime_comms_plan(
                program, cb.fetch_names,
                batch_size=_feed_batch(feeds))
        if plan is None:
            return None
        return plan, comms.bound_byte_cells(plan)
    except Exception:
        return None


def _resolve_cost(cb, program, feeds):
    """Once per compiled block: the analytic flops-per-step of this
    program at the REAL feed batch (the verifier stamps a batch=1
    baseline; the plan cache makes the re-plan at the true batch a
    fingerprint-keyed one-off).  Also publishes the per-op-class flop
    shares, stashes them on the block for the cost-crosscheck's
    divergence attribution, and re-stamps the verify-time HBM plan at
    the real batch.  Returns (flops, peak_flops_per_s, mxu_share) or
    None — cost modeling must never break dispatch."""
    try:
        from ..analysis.cost import plan_cost
        batch = _feed_batch(feeds)
        try:
            _restamp_memory(program, cb.fetch_names, batch)
        except Exception:
            pass
        plan = plan_cost(program, cb.fetch_names, batch_size=batch)
        cb.cost_share = dict(plan.share())
        if not plan.flops:
            return None
        share = plan.share()
        # the family reports THE most recently planned step: drop stale
        # op-class series first, or a conv model's shares would keep
        # exporting next to a later transformer's (summing to ~2 and
        # attributing flops to classes the current program lacks)
        for labels, _cell in _CLASS_SHARE_GAUGE.series():
            if labels.get("op_class") not in share:
                _CLASS_SHARE_GAUGE.fold(labels, None)
        for cls, s in share.items():
            _CLASS_SHARE_GAUGE.set(s, op_class=cls)
        _ANALYTIC_FLOPS_GAUGE.set(float(plan.flops))
        mxu = sum(share.get(c, 0.0)
                  for c in ("matmul", "conv", "attention"))
        return float(plan.flops), _device_peak(), mxu
    except Exception:
        return None


def _scope_evict_cb(exe_ref, scope_tok):
    exe = exe_ref()
    if exe is not None:
        exe._evict_scope(scope_tok)


def aggregate_dispatch_stats() -> Dict[str, Any]:
    """Sum dispatch counters over every live Executor (profiler API).

    Live-executor semantics on purpose: an executor's series dies with it
    here (matching the reference's per-executor profiler state), while the
    monitor registry keeps every series for export — use
    ``monitor.counter_totals()`` for process-lifetime totals."""
    fields = _DispatchStats._INT_FIELDS + _DispatchStats._US_FIELDS
    out: Dict[str, Any] = dict.fromkeys(fields, 0)
    n = 0
    for exe in list(_EXECUTORS):
        snap = exe._stats.snapshot()
        for f in fields:
            out[f] += snap[f]
        n += 1
    out["executors"] = n
    return out


class FetchHandle:
    """A lazy fetch: wraps the still-in-flight ``jax.Array`` of a fetched
    value and defers the device→host sync to first materialization.

    ``Executor.run(..., return_numpy=False)`` returns these, so back-to-back
    ``run()`` calls pipeline on device — the host never waits for step *i*
    before dispatching step *i+1* (the ~115 ms tunnel RTT per sync is the
    whole point).  ``.numpy()`` / ``np.asarray(handle)`` materialize (and
    cache) the host value; attribute access (``.shape``, ``.dtype``,
    ``.sharding``, ``.block_until_ready``) forwards to the wrapped array
    without syncing.  Fetch buffers are never donated, so a handle stays
    valid across later steps that donate and overwrite the parameter state.

    Multi-process note: on an array spanning processes, ``.numpy()`` is a
    COLLECTIVE (``process_allgather``) — every rank must materialize
    cross-rank fetches in the SAME order, or ranks deadlock waiting on
    each other.  ``.local_numpy()`` materializes only this process's
    shards with no communication and may be called rank-locally.
    """

    __slots__ = ("_value", "_np", "_stats")

    def __init__(self, value, stats: Optional[_DispatchStats] = None):
        self._value = value
        self._np = None
        self._stats = stats

    @property
    def value(self):
        """The wrapped (possibly still in-flight) device array."""
        return self._value

    @property
    def is_materialized(self) -> bool:
        return self._np is not None

    def numpy(self) -> np.ndarray:
        if self._np is None:
            t0 = time.perf_counter()
            with _resil.WATCHDOG.watch("fetch.materialize"):
                _resil.maybe_inject("fetch.materialize")
                self._np = _fetch_to_numpy(self._value)
            t1 = time.perf_counter()
            if self._stats is not None:
                self._stats.incr("fetch_materializations")
                self._stats.block("materialize_block_us", (t1 - t0) * 1e6)
            if _monitor.TRACER.enabled:
                _monitor.TRACER.add_complete(
                    "fetch.materialize", "fetch", t0, t1)
        return self._np

    def local_numpy(self) -> np.ndarray:
        """Per-rank materialization: sync only THIS process's addressable
        shards, concatenated along the sharded axis (batch order follows
        the shard index order).  Unlike ``.numpy()`` — which allgathers a
        cross-process array and is therefore a COLLECTIVE every rank must
        enter in the same order — this never communicates, so ranks may
        call it independently (e.g. rank-local logging/dumping).  On a
        single process (or a fully-addressable array) it is ``.numpy()``.
        """
        v = self._value
        if not isinstance(v, jax.Array) or v.is_fully_addressable:
            return self.numpy()
        t0 = time.perf_counter()
        out = _assemble_local_shards(v)
        t1 = time.perf_counter()
        if self._stats is not None:
            self._stats.incr("fetch_materializations")
            self._stats.block("materialize_block_us", (t1 - t0) * 1e6)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                "fetch.materialize_local", "fetch", t0, t1)
        return out

    def __array__(self, dtype=None, copy=None):
        a = self.numpy()
        if dtype is not None and a.dtype != np.dtype(dtype):
            return a.astype(dtype)
        if copy:
            return np.array(a)
        return a

    def __getattr__(self, name):
        # everything else (shape/dtype/sharding/block_until_ready/...)
        # forwards to the device array WITHOUT forcing a sync.  Dunder
        # and slot names never forward: an unset _value slot (e.g. a
        # pickle-protocol probe on a bare __slots__ instance) would
        # otherwise re-enter __getattr__ forever
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._value, name)

    def __getitem__(self, idx):
        return self._value[idx]

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __bool__(self):
        # implicit dunders bypass __getattr__ (type-level lookup), so
        # without this a zero-valued scalar handle would be truthy
        return bool(self.numpy())

    def __len__(self):
        return len(self._value)

    def __repr__(self):
        state = "materialized" if self._np is not None else "in-flight"
        return (f"FetchHandle({state}, shape="
                f"{getattr(self._value, 'shape', None)}, dtype="
                f"{getattr(self._value, 'dtype', None)})")


def _assemble_local_shards(v) -> np.ndarray:
    """Assemble this process's addressable shards of a global array into
    one host array, pasting each shard into the bounding box of the local
    index set — correct for any rectangular tiling, including meshes
    sharding two or more axes at once (a single-axis concatenate would
    silently mis-stack those).  Replicated copies (identical index) are
    deduped.  Slice objects are normalized to (start, stop) int tuples:
    they are position keys, and raw slices are unhashable before
    Python 3.12."""
    shape = v.shape
    parts = {}
    for s in v.addressable_shards:
        key = tuple((sl.start or 0,
                     sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(s.index, shape))
        if key not in parts:             # replicated shard: one copy
            parts[key] = np.asarray(s.data)
    if len(parts) == 1:
        return next(iter(parts.values()))
    ndim = len(shape)
    lo = [min(k[d][0] for k in parts) for d in range(ndim)]
    hi = [max(k[d][1] for k in parts) for d in range(ndim)]
    bbox_size = 1
    for l, h in zip(lo, hi):
        bbox_size *= h - l
    pasted = sum(int(np.prod(a.shape)) if a.shape else 1
                 for a in parts.values())
    if pasted != bbox_size:
        # shards are disjoint rectangles, so covering the bbox means the
        # pasted volume equals it exactly; anything less would leave
        # np.empty garbage in the gaps (e.g. a device layout interleaving
        # processes along an axis) — refuse rather than return junk
        raise ValueError(
            "this process's shards do not contiguously tile their "
            f"bounding box ({pasted} of {bbox_size} elements); no dense "
            "local array exists — use .numpy() (collective) instead")
    first = next(iter(parts.values()))
    out = np.empty([h - l for l, h in zip(lo, hi)], dtype=first.dtype)
    for key, arr in parts.items():
        out[tuple(slice(k0 - l, k1 - l)
                  for (k0, k1), l in zip(key, lo))] = arr
    return out


def _fetch_handle_binop(name):
    # comparisons and arithmetic are implicit dunders — resolved on the
    # type, never via __getattr__ — so they must be forwarded explicitly
    # or `h == x` falls back to identity and `h + x` raises.  Forwarding
    # to the wrapped jax.Array keeps the result lazy on device.
    def op(self, other):
        if isinstance(other, FetchHandle):
            other = other._value
        return getattr(self._value, name)(other)
    op.__name__ = name
    return op


for _n in ("__eq__", "__ne__", "__lt__", "__le__", "__gt__", "__ge__",
           "__add__", "__radd__", "__sub__", "__rsub__",
           "__mul__", "__rmul__", "__truediv__", "__rtruediv__",
           "__floordiv__", "__rfloordiv__", "__mod__", "__rmod__",
           "__pow__", "__rpow__", "__matmul__", "__rmatmul__"):
    setattr(FetchHandle, _n, _fetch_handle_binop(_n))
del _n


class _DispatchPlan:
    """Memoized steady-state dispatch: everything ``run()`` derives from
    (program fingerprint, feed-name tuple, fetch set, scope, flags) that
    does not change step to step — the compiled block, the full cache key,
    the resolved (graph-pass-optimized) program, and the expected feed
    signatures.  A plan hit skips the listen_and_serv scan, feed-name
    sorting, persistable classification, the lock, AND — for a
    CompiledProgram — the per-call ``_optimized`` re-resolution (its dict
    probe + attr chase): the plan is keyed directly on the
    CompiledProgram's serial + source-program fingerprint, and carries
    the optimized program it resolved once."""

    __slots__ = ("cb", "key", "feed_names", "feed_sigs", "program")

    def __init__(self, cb, key, feed_names, feed_sigs, program):
        self.cb = cb
        self.key = key
        self.feed_names = feed_names       # insertion order, not sorted
        self.feed_sigs = feed_sigs
        self.program = program             # post-_optimized program


class LowerCtx:
    """Per-trace context handed to op lowerings."""

    is_abstract = False

    def __init__(self, seed, mesh=None, is_startup=False, amp=False,
                 collective_axis=None):
        self._seed = seed
        self._key = None  # derived lazily: most ops never need RNG
        self._counter = 0
        self.mesh = mesh
        self.is_startup = is_startup
        self.amp = amp
        # set when the block runs under collective shard_map mode: the mesh
        # axis (or ring_id->axis map) the c_* collective ops reduce over
        self.collective_axis = collective_axis

    def _base_key(self):
        if self._key is None:
            seed = self._seed
            if isinstance(seed, jax.Array) and jax.dtypes.issubdtype(
                    seed.dtype, jax.dtypes.prng_key):
                self._key = seed
            else:
                # rbg: much cheaper per-block random bits on TPU than
                # threefry — dropout RNG was ~40% of a BERT step with the
                # default impl
                self._key = jax.random.key(seed, impl="rbg")
        return self._key

    def rng(self):
        self._counter += 1
        return jax.random.fold_in(self._base_key(), self._counter)

    def rng_tagged(self, tag):
        """Deterministic per-tag stream, independent of trace order: an op
        and its grad op fold the same tag and regenerate IDENTICAL bits, so
        masks are recomputed in backward instead of stored (dropout masks
        were ~15% of a BERT step as HBM traffic).  The extra 0x5EED fold
        keeps the tag stream disjoint from the counter stream above."""
        return jax.random.fold_in(
            jax.random.fold_in(self._base_key(), 0x5EED), tag)


def _seed_to_key(seed):
    if isinstance(seed, jax.Array) and jax.dtypes.issubdtype(seed.dtype, jax.dtypes.prng_key):
        return seed
    return jax.random.key(seed)


class _ExecState:
    """SSA value environment while lowering a block.

    ``constraints`` ({var name -> (spec tuple, NamedSharding)}) is the
    GSPMD partitioner's activation-sharding table: every write of a
    listed activation pins its layout with
    ``jax.lax.with_sharding_constraint`` (t5x discipline, SNIPPETS.md
    [1]) so XLA's propagation cannot drift from the layout the
    rule-table planner priced."""

    def __init__(self, values: Dict[str, Any], constraints=None):
        self.values = values
        self.written: set = set()
        self.constraints = constraints
        # fwd-output name -> ctx._counter before that op's lowering; lets
        # generic grad ops replay a sampling op's rng stream (see run_op)
        self.rng_marks: Dict[str, int] = {}

    def read(self, block: Block, name: str):
        if name == "" or name is None:
            return None
        if name not in self.values:
            raise KeyError(
                f"op input var {name!r} has no value: not fed, not in scope, "
                f"and not produced by a preceding op")
        return self.values[name]

    def write(self, name: str, value):
        if name == "" or name is None:
            return
        if self.constraints is not None:
            c = self.constraints.get(name)
            if c is not None and getattr(value, "ndim", -1) == len(c[0]):
                import jax
                value = jax.lax.with_sharding_constraint(value, c[1])
        self.values[name] = value
        self.written.add(name)


def run_block(ctx: LowerCtx, block: Block, state: _ExecState) -> None:
    """Trace every op of ``block`` into the surrounding JAX computation.

    This is the hot loop of ref ``executor.cc:432`` — except it runs once at
    trace time, not every step.
    """
    for op in block.ops:
        run_op(ctx, block, op, state)


def _op_context(block, op) -> str:
    """Enforce-style diagnostic context (ref platform/enforce.h — the
    reference enriches every kernel error with op/var context)."""
    parts = [f"op={op.type!r}"]
    for slot, names in op.inputs.items():
        for n in names:
            shape = None
            if n and block.has_var(n):
                shape = block.var(n).shape
            parts.append(f"in {slot}:{n} shape={shape}")
    parts.append(f"outs={[n for ns in op.outputs.values() for n in ns]}")
    return "\n  ".join(parts)


def _sanitize_outputs(op, outs):
    """FLAGS_check_nan_inf at the framework level: bind each float output
    to the producing FLUID op (jax_debug_nans reports XLA ops, which users
    can't map back to their program).  The debug branch only executes on a
    hit, so the clean path pays one reduction per output."""
    import jax
    for slot, vals in outs.items():
        for i, v in enumerate(vals):
            if v is None or not hasattr(v, "dtype") or \
                    not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            bad = ~jnp.all(jnp.isfinite(v))
            jax.lax.cond(
                bad,
                lambda t=op.type, s=slot, j=i: jax.debug.print(
                    "FLAGS_check_nan_inf: non-finite value in output "
                    "{s}[{j}] of op {t}", t=t, s=s, j=j),
                lambda: None)


def run_op(ctx: LowerCtx, block: Block, op: Operator, state: _ExecState) -> None:
    if op.type in ("feed", "fetch"):
        return
    try:
        _run_op_inner(ctx, block, op, state)
    except Exception as e:
        if getattr(e, "_pt_op_context", False):
            raise               # already annotated by the failing inner op
        msg = (f"{type(e).__name__} while lowering op {op.type!r}: {e}\n"
               f"  {_op_context(block, op)}")
        err = RuntimeError(msg)
        err._pt_op_context = True
        raise err from e


def _run_op_inner(ctx, block, op, state) -> None:
    if op.type.endswith("_grad") and not registry.has_op(op.type):
        _run_generic_grad(ctx, block, op, state)
        return
    info = registry.get_op_info(op.type)
    if info.raw:
        info.lower(ctx, block, op, state)
        return
    ins = {slot: [state.read(block, n) for n in names]
           for slot, names in op.inputs.items()}
    if ctx.amp:
        from .. import amp as _amp
        ins = _amp.cast_ins(op.type, ins)
    if info.stateful_rng:
        # remember where the counter stream stood so a generic-vjp grad op
        # can REPLAY the same draws when it retraces this forward (else the
        # backward would differentiate a different sample set — the dropout
        # hand-maker avoids this with its saved mask; every other sampling
        # op goes through here)
        mark = ctx._counter
        for names in op.outputs.values():
            for n in names:
                if n:
                    state.rng_marks[n] = mark
    outs = info.lower(ctx, ins, op.attrs) or {}
    from ..flags import get_flags
    if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
        _sanitize_outputs(op, outs)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if i < len(vals):
                state.write(n, vals[i])


def _run_generic_grad(ctx, block: Block, op: Operator, state: _ExecState):
    ins = {}
    for slot, names in op.inputs.items():
        if slot.startswith("OG$"):
            # an output grad may be absent (output unused downstream)
            ins[slot] = [state.values.get(n) for n in names]
        else:
            ins[slot] = [state.read(block, n) for n in names]
    # NO amp cast here: generic_grad_lower casts INSIDE its vjp closure,
    # which keeps master-weight grads f32 (a pre-cast would differentiate
    # wrt the bf16 copy and round every weight grad)
    mark = None
    finfo = registry._REGISTRY.get(op.attrs.get("__fwd_type__"))
    if finfo is not None and finfo.stateful_rng:
        for slot, names in op.inputs.items():
            if not slot.startswith("OG$"):
                continue
            for gn in names:
                base = gn[:-5] if gn and gn.endswith("@GRAD") else None
                if base is not None and base in state.rng_marks:
                    mark = state.rng_marks[base]
                    break
            if mark is not None:
                break
    if mark is None:
        outs = registry.generic_grad_lower(ctx, ins, op.attrs)
    else:
        # rewind the counter so the vjp's retraced forward draws the SAME
        # randomness the forward op consumed, then restore it
        saved = ctx._counter
        ctx._counter = mark
        try:
            outs = registry.generic_grad_lower(ctx, ins, op.attrs)
        finally:
            ctx._counter = saved
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if n and i < len(vals) and vals[i] is not None:
                state.write(n, vals[i])


class _CompiledBlock:
    """A lowered+jitted block specialized to a feed/fetch/persist signature."""

    def __init__(self, program: Program, block_idx: int,
                 feed_names: Tuple[str, ...], fetch_names: Tuple[str, ...],
                 persist_ro: Tuple[str, ...], persist_rw: Tuple[str, ...],
                 mesh=None, in_shardings=None, donate=True,
                 collective=None, feed_ndims=None, numerics_mode="off"):
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.persist_ro = persist_ro
        self.persist_rw = persist_rw
        self.collective_nranks = None
        self._donating = bool(donate and persist_rw)
        block = program.blocks[block_idx]
        amp_on = bool(program._attrs.get("amp", False))
        # numerics observability (analysis.numerics): the lowered step
        # folds tensor-health stats into ONE extra packed output.  Mode
        # is latched at trace time (it is part of the executor's cache
        # key); the layout lands in a box the first trace fills, read
        # back as `numerics_layout` after the first call.
        num_on = numerics_mode != "off"
        num_spec = program._attrs.get("numerics")
        self._num_layout_box: list = []
        self.numerics_layout = None

        collective_axis = "dp" if collective else None

        # GSPMD activation constraints (parallel.partitioner): the
        # partition stamp's per-activation specs resolve to
        # NamedShardings once here; _ExecState.write pins each listed
        # activation at trace time.  Only in the pjit path — the
        # shard_map collective path is already per-device.
        part = program._attrs.get("partition")
        self.partitioned = bool(part)
        constraints = None
        if part and mesh is not None and not collective and \
                part.get("activations"):
            from ..parallel.mesh import sharding_for
            constraints = {
                n: (tuple(spec), sharding_for(mesh, tuple(spec)))
                for n, spec in part["activations"].items()}

        def step(feeds, ro, rw, seed):
            ctx = LowerCtx(seed, mesh=mesh, amp=amp_on,
                           collective_axis=collective_axis)
            values = {}
            values.update(dict(zip(persist_ro, ro)))
            values.update(dict(zip(persist_rw, rw)))
            values.update(dict(zip(feed_names, feeds)))
            state = _ExecState(values, constraints=constraints)
            run_block(ctx, block, state)
            fetches = [state.values[n] for n in fetch_names]
            new_rw = [state.values[n] for n in persist_rw]
            if donate and persist_rw:
                # a fetch that IS an rw persistable (monitoring a weight,
                # dumping a state var) traces to the identical value in
                # both outputs; XLA gives both one buffer, and the NEXT
                # step's donation of the rw input would kill it while a
                # lazy FetchHandle still points at it.  An explicit copy
                # forces the fetch into its own (never-donated) buffer.
                rw_ids = {id(v) for v in new_rw}
                fetches = [jnp.copy(f) if id(f) in rw_ids else f
                           for f in fetches]
            # dedicated throttle probe: a tiny COMPUTED output (a bare
            # pass-through would alias the seed input buffer and read as
            # ready instantly).  Its buffer becomes ready only when the
            # step's execution completes, it is never donated, and later
            # steps never consume it — so the in-flight throttle always
            # has a waitable array even on fetch-less train_from_dataset
            # loops whose rw state the next step donates.  seed is always
            # a uint32 scalar here (_finish_run mints it).
            probe = seed + jnp.uint32(1)
            if num_on:
                # force=True keeps the output arity FIXED (out_shardings
                # / shard_map out_specs are declared before tracing): a
                # block with nothing to observe emits an all-zero header
                layout, packed = _numerics().build_step_stats(
                    state.values, state.written, feed_names, persist_rw,
                    rw, new_rw, numerics_mode, spec=num_spec, force=True)
                self._num_layout_box[:] = [layout]
                return fetches, new_rw, probe, packed
            return fetches, new_rw, probe

        if collective:
            # Collective (multi-process DP) mode — ref §3.3: the whole block
            # becomes one shard_map over the dp axis: per-device compute with
            # explicit c_* collectives, batch feeds sharded on dim 0, params
            # replicated.  Fetches come back stacked per-rank (the reference
            # ParallelExecutor also returns per-device fetch values).
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec as P
            try:
                from jax import shard_map
            except ImportError:  # pragma: no cover
                from jax.experimental.shard_map import shard_map
            nranks = int(collective.get("nranks", 0)) or len(jax.devices())
            devs = jax.devices()
            if nranks > len(devs):
                raise ValueError(
                    f"collective mode needs {nranks} devices, have "
                    f"{len(devs)}")
            self.collective_nranks = nranks
            cmesh = Mesh(np.array(devs[:nranks]), ("dp",))
            # trainable params stay replicated by construction (psum'd
            # grads); other persistables (BN running stats — non-trainable
            # params, metric states) see per-rank batch shards and would
            # diverge — average them across ranks (ints: pmax, they advance
            # identically e.g. step counters)
            def _synced_by_grads(n):
                if not block.has_var(n):
                    return False
                v = block.var(n)
                return getattr(v, "is_parameter", False) and \
                    getattr(v, "trainable", True)
            rw_is_param = [_synced_by_grads(n) for n in persist_rw]

            def sharded_step(feeds, ro, rw, seed):
                # per-rank RNG stream (reference multi-process trainers have
                # independent seeds) — fold in the rank
                rank_seed = seed + lax.axis_index("dp").astype(
                    jnp.uint32) * jnp.uint32(1000003)
                out = step(feeds, ro, rw, rank_seed)
                fetches, new_rw = out[0], out[1]
                synced_rw = []
                for v, is_p in zip(new_rw, rw_is_param):
                    if is_p:
                        synced_rw.append(v)
                    elif jnp.issubdtype(v.dtype, jnp.floating):
                        synced_rw.append(lax.pmean(v, "dp"))
                    else:
                        synced_rw.append(lax.pmax(v, "dp"))
                # probe from the PRE-fold seed: replicated by construction
                # (its per-rank counterpart diverges and would need a
                # collective to satisfy the replicated out_spec)
                res = ([f[None] for f in fetches], synced_rw,
                       seed + jnp.uint32(1))
                if len(out) == 4:
                    # per-rank stats stack like fetches; the engine's
                    # frame decoder combines them (counts sum, absmax
                    # maxes) so a NaN on ANY rank trips the sentinel
                    res = res + (out[3][None],)
                return res

            # scalar feeds replicate; batched feeds shard on dim 0
            fspecs = [P("dp") if nd >= 1 else P()
                      for nd in (feed_ndims or [1] * len(feed_names))]
            out_specs = ([P("dp")] * len(fetch_names),
                         [P()] * len(persist_rw), P())
            if num_on:
                out_specs = out_specs + (P("dp"),)
            sm_kwargs = dict(
                mesh=cmesh,
                in_specs=(fspecs, [P()] * len(persist_ro),
                          [P()] * len(persist_rw), P()),
                out_specs=out_specs)
            try:
                inner = shard_map(sharded_step, check_vma=False, **sm_kwargs)
            except TypeError:  # older jax: the kwarg is check_rep
                inner = shard_map(sharded_step, check_rep=False, **sm_kwargs)
            jkw = {}
            if donate and persist_rw:
                jkw["donate_argnums"] = (2,)
            self.jitted = jax.jit(inner, **jkw)
            return

        kwargs = {}
        if donate and persist_rw:
            kwargs["donate_argnums"] = (2,)
        self.in_shardings = in_shardings     # kept for multi-host feeds
        self.mesh = mesh
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
            # updated state must come back in its declared layout, or the
            # next call's arg shardings mismatch the jit signature; the
            # probe output is a replicated scalar (None = let GSPMD pick),
            # and so is the numerics stats vector when enabled
            kwargs["out_shardings"] = (
                (None, list(in_shardings[2]), None, None) if num_on
                else (None, list(in_shardings[2]), None))
        if program._attrs.get("is_distributed") and \
                jax.default_backend() != "cpu":
            # PS trainer programs embed host-RPC send/recv io_callbacks,
            # which the tunneled TPU backend can't service — PS mode is the
            # reference's CPU sparse-workload path (ref §3.4), so pin the
            # step to the host CPU backend
            cpu = jax.devices("cpu")[0]
            # jit rejects device= combined with donation or shardings
            kwargs.pop("donate_argnums", None)
            kwargs.pop("in_shardings", None)
            kwargs.pop("out_shardings", None)
            self.jitted = jax.jit(step, device=cpu, **kwargs)
            return
        self.jitted = jax.jit(step, **kwargs)

    _hbm_recorded = False
    _compiled_aot = None

    def __call__(self, feeds, ro, rw, seed):
        if not self._hbm_recorded and _hbm().plans_enabled():
            # capture the executable's HBM allocation plan (ref
            # allocator_facade stats): device.memory_stats() is unavailable
            # through the axon tunnel, but the AOT-compiled executable's
            # memory_analysis IS the on-chip buffer assignment — arguments
            # + temps + outputs is what the runtime allocates for a step.
            # The AOT object is then used for execution, so recording costs
            # no extra compile.  Routed through hbm.record_xla_plan (the
            # one ingestion point for measured bytes; FLAGS_hbm_record_plans
            # with PADDLE_TPU_RECORD_HBM kept as the legacy env alias).
            self._hbm_recorded = True
            try:
                compiled = self.jitted.lower(feeds, ro, rw, seed).compile()
                _hbm().record_xla_plan(
                    ",".join(self.fetch_names) or "<block>",
                    compiled.memory_analysis())
                self._compiled_aot = compiled
            except Exception:
                pass
        if self._compiled_aot is not None:
            if self._donating:
                # rw buffers are donated: a mid-execution failure leaves
                # them deleted, so a fallback retry would mask the real
                # error with 'Array has been deleted' — just run it
                return self._compiled_aot(feeds, ro, rw, seed)
            try:
                return self._compiled_aot(feeds, ro, rw, seed)
            except Exception:
                self._compiled_aot = None
        return self.jitted(feeds, ro, rw, seed)


def _collect_persistables(program: Program, block: Block, scope: Scope,
                          feed_names) -> Tuple[List[str], List[str], set]:
    """Classify persistable vars referenced by a block into read-only vs
    read-write (written by some op); also return the set of vars whose
    INCOMING value matters — read before any top-level write (startup
    programs init a param then copy it: the copy must not force the param
    to pre-exist in the scope).  Sub-block reads are ALWAYS incoming:
    loop lowerings read every carried var's initial value, so no
    write-before-read exemption applies inside sub-blocks."""
    read, written, incoming = set(), set(), set()

    def visit(b: Block, is_sub: bool):
        for op in b.ops:
            for n in op.input_arg_names():
                read.add(n)
                if is_sub or n not in written:
                    incoming.add(n)
            for v in op.attrs.values():
                if isinstance(v, Block):
                    visit(v, True)
            for n in op.output_arg_names():
                written.add(n)

    visit(block, False)
    ro, rw = [], []
    for name in sorted(read | written):
        if name in feed_names or not name:
            continue
        if not block.has_var(name):
            continue
        v = block.var(name)
        if not v.persistable:
            continue
        (rw if name in written else ro).append(name)
    return ro, rw, incoming


class Executor:
    """ref ``python/paddle/fluid/executor.py:295`` Executor.

    ``place`` is advisory: JAX picks the default backend (TPU when present).
    """

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, _CompiledBlock] = {}  # guarded-by: _lock
        self._plans: Dict[Any, _DispatchPlan] = {}  # guarded-by: _lock
        # RLock, not Lock: the scope-eviction weakref.finalize callback
        # takes this lock, and cyclic GC (Scope's parent<->kids cycle
        # makes the gc module the collector) can fire it at an allocation
        # point INSIDE a critical section on the same thread — a
        # non-reentrant lock would self-deadlock there
        self._lock = threading.RLock()
        self._step_seed = 0
        # FLAGS_gang_step_barrier: monotonic barrier index + memoized
        # gang client (resolved once; _UNSET = not yet resolved)
        self._barrier_step = 0
        self._gang = _UNSET
        # pre-collective timestamp gate (analysis.comms): consecutive
        # failure count + self-disarm latch — telemetry must never
        # stall training against a half-dead gang
        self._comm_gate_fails = 0
        self._comm_gate_off = False
        self._stats = _DispatchStats()
        # async dispatch throttle: representative output arrays of the last
        # N dispatched steps; run() blocks on the oldest once more than
        # FLAGS_executor_max_inflight_steps are in flight, so lazy-fetch
        # loops cannot run arbitrarily ahead of HBM
        self._inflight: collections.deque = \
            collections.deque()  # guarded-by: _lock
        self._evict_reg: set = set()
        # step-boundary hooks: called after every completed dispatch,
        # once the scope holds the step's (possibly in-flight) outputs —
        # the checkpoint daemon's capture point (resilience.py)
        self._step_hooks: List[Any] = []  # guarded-by: _lock
        # live device-time attribution: inter-dispatch interval window
        # (median feeds the step_device_ms / step_mfu gauges)
        self._last_dispatch_t: Optional[float] = None  # guarded-by: _lock
        self._step_win: collections.deque = \
            collections.deque(maxlen=9)  # guarded-by: _lock
        _EXECUTORS.add(self)
        # registry hygiene: when this executor dies, its 13 label series
        # fold into executor="retired" (the callback must not hold a ref
        # to the executor — it holds only the stats object)
        weakref.finalize(self, _DispatchStats.retire, self._stats)

    def close(self):
        with self._lock:
            self._cache.clear()
            self._plans.clear()
            self._inflight.clear()
        # int64 feed-wrap dedup tokens are NOT re-armed here: the verifier
        # classifies feeds statically (program._attrs["verify"]), so
        # verified programs skip the runtime check wholesale and the
        # legacy spot-check for unverified programs is once per
        # (program, feed) per process — the value range is a property of
        # the data source, not of which executor ran it
        # _evict_reg is NOT cleared: its finalizers live until their scope
        # dies, so clearing would stack a duplicate finalize on a
        # long-lived scope every close()/run() cycle — dead scopes already
        # remove their own token in _evict_scope

    def _evict_scope(self, scope_tok):
        """Drop every compiled block and dispatch plan keyed to a dead
        scope.  Serial keys never collide (unlike id()), which also means
        entries for dead scopes would otherwise accumulate FOREVER — a
        fresh-scope-per-request loop would leak one compiled executable
        per request; a ``weakref.finalize`` on the scope calls this."""
        with self._lock:
            for k in [k for k in self._cache if k[4] == scope_tok]:
                del self._cache[k]
            for k in [k for k in self._plans if k[3] == scope_tok]:
                del self._plans[k]
        self._evict_reg.discard(scope_tok)

    # -- step-boundary hooks -------------------------------------------------
    def add_step_hook(self, fn) -> None:
        """Register ``fn(executor, scope)`` to run after every completed
        dispatch, at the step boundary where the scope holds the step's
        full (possibly still in-flight on device) output state — the
        safe point to snapshot persistables without tearing a step.
        Note EVERY ``run()`` counts, including startup programs: attach
        cadence-counting hooks (``CheckpointDaemon.attach``) after
        startup.  Hooks run on the dispatching thread and must be cheap;
        a hook exception fails the step."""
        with self._lock:
            if fn not in self._step_hooks:
                self._step_hooks.append(fn)

    def remove_step_hook(self, fn) -> None:
        with self._lock:
            if fn in self._step_hooks:
                self._step_hooks.remove(fn)

    # -- dispatch telemetry --------------------------------------------------
    def dispatch_stats(self) -> Dict[str, Any]:
        """Snapshot of this executor's dispatch counters (see
        ``_DispatchStats``).  Adds the current in-flight depth and the
        configured throttle so callers can reason about pipelining."""
        from ..flags import get_flags
        out = self._stats.snapshot()
        out["steps_in_flight"] = len(self._inflight)
        # distinct lowered executables this executor holds — the serving
        # smoke's "compile count == shape buckets" gate reads this
        with self._lock:
            out["compiled_blocks"] = len(self._cache)
        out["max_in_flight"] = int(get_flags(
            "FLAGS_executor_max_inflight_steps")
            ["FLAGS_executor_max_inflight_steps"])
        return out

    def reset_dispatch_stats(self):
        self._stats.reset()

    # -- main entry ----------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            seed: Optional[int] = None):
        t0 = time.perf_counter()
        from ..compiler import CompiledProgram
        from ..flags import get_flags
        mesh = None
        in_shardings = None
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else f
            for f in (fetch_list or []))
        cp_tok = None
        compiled = None
        if isinstance(program, CompiledProgram):
            compiled = program
            # fast path keys on the SOURCE program + the CompiledProgram
            # serial and resolves _optimized only on a plan miss: the
            # memoized plan carries the optimized program, so a
            # steady-state step skips the per-call re-resolution (dict
            # probe + attr chase) entirely.  The serial, not the mesh:
            # two CompiledPrograms with structurally-equal meshes but
            # different sharding configs (zero stage, input specs) must
            # not share a compiled block — and reconfiguration bumps it.
            program = compiled._program
            cp_tok = getattr(compiled, "_serial", None)
            if cp_tok is None:
                cp_tok = id(compiled)
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        check_nan = bool(
            get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"])
        scope_tok = getattr(scope, "_serial", None)
        if scope_tok is None:           # foreign scope-like object
            scope_tok = id(scope)

        # ---- steady-state fast path: one dict probe + a feed-sig check.
        # The plan memoizes every per-run derivation (sorted feed names,
        # persistable classification, pserver scan, full cache key,
        # _optimized resolution), so a repeat step does no re-sorting or
        # re-classification — only the unavoidable shape/dtype check
        # (feeds CAN change shape, e.g. a last partial batch, and must
        # fall back to the slow path).
        # mesh and collective must be part of the key: neither is covered
        # by the program fingerprint (a CompiledProgram can share its
        # fingerprint with the raw Program, and the transpiler sets
        # _attrs["collective"] without a version bump), and a plan hit
        # running the wrong sharding would be silent.  The collective
        # token derives from the SOURCE program's attrs — _optimized
        # clones them, and keying on the source keeps hit and miss paths
        # consistent.
        collective = program._attrs.get("collective")
        coll_tok = (tuple(sorted(collective.items()))
                    if collective else None)
        # fusion config in the key: a FLAGS_graph_fusion/_autotune/
        # _rank_threshold flip changes what _optimized/fuse_program
        # produce without touching the program fingerprint — stale plans
        # would silently run the old rewrite
        fus_tok = _fusion().config_token()
        # numerics mode is read at trace time (step() folds the stats
        # output in) — a FLAGS_numerics flip must re-lower, not reuse a
        # block with the wrong output arity
        num_tok = _numerics().mode()
        fast_key = (program.fingerprint(), tuple(feed), fetch_names,
                    scope_tok, check_nan, cp_tok, coll_tok, fus_tok,
                    num_tok)
        plan = self._plans.get(fast_key)
        if plan is not None and plan.feed_sigs == tuple(
                _feed_sig(feed[n]) for n in plan.feed_names):
            self._stats.incr("cache_hits")
            return self._dispatch(plan.cb, plan.key, feed, scope,
                                  plan.program, return_numpy, seed, t0)

        # ---- slow path: full classification + (maybe) lowering -------------
        feed_shapes = {n: _feed_sig(v)[0] for n, v in feed.items()}
        if compiled is not None:
            program = compiled._optimized(fetch_names,
                                          feed_shapes=feed_shapes)
            mesh = compiled._mesh
            in_shardings = compiled._build_in_shardings
            collective = program._attrs.get("collective")
        # a pserver program is a blocking host loop, not a jittable block
        # (ref listen_and_serv_op.cc RunImpl blocking in Executor::Run)
        lsv = next((op for op in program.global_block().ops
                    if op.type == "listen_and_serv"), None)
        if lsv is not None:
            from ..distributed import ps as _ps
            return _ps.run_pserver(lsv, scope)
        if compiled is None:
            # plain-Program dispatch gets the same fusion slot
            # CompiledProgram._optimized runs (this is how bench.py's
            # direct exe.run() loops reach the pass), at the REAL feed
            # batch; fuse_program's result cache makes the repeat entry
            # a dict probe
            from ..compiler import _timed_pass
            with _timed_pass({}, "graph_fusion"):
                program = _fusion().fuse_program(
                    program, fetch_names, feed_shapes=feed_shapes)
        feed_names = tuple(sorted(feed))

        block = program.global_block()
        # the flag is read at trace time (_run_op_inner) — it must be part
        # of the cache key, or toggling it after a first run is a no-op.
        # Scope identity is its monotonic serial (NOT id(): after GC a new
        # scope can reuse a dead scope's id and silently hit a compiled
        # entry classified for the dead scope's persistables); the
        # CompiledProgram keys by its own serial for the same reason.
        key = (program.fingerprint(), feed_names,
               tuple(_feed_sig(feed[n]) for n in feed_names),
               fetch_names, scope_tok, cp_tok, check_nan, coll_tok,
               fus_tok, num_tok)
        with self._lock:
            cb = self._cache.get(key)
            if cb is None:
                self._stats.incr("cache_misses")
                self._stats.incr("traces")
                ro, rw, read_set = _collect_persistables(
                    program, block, scope, feed_names)
                shardings = None
                if in_shardings is not None:
                    shardings = in_shardings(feed_names, ro, rw)
                cb = _CompiledBlock(
                    program, 0, feed_names, fetch_names,
                    tuple(ro), tuple(rw), mesh=mesh,
                    in_shardings=shardings, collective=collective,
                    feed_ndims=tuple(len(_feed_sig(feed[n])[0])
                                     for n in feed_names),
                    numerics_mode=num_tok)
                cb.rw_read = frozenset(n for n in rw if n in read_set)
                # first call pays trace+compile: _finish_run times it and
                # records the persistent-cache outcome (compile telemetry)
                cb.pending_compile = True
                self._cache[key] = cb
            else:
                self._stats.incr("cache_hits")
            plan_names = tuple(feed)
            self._plans[fast_key] = _DispatchPlan(
                cb, key, plan_names,
                tuple(_feed_sig(feed[n]) for n in plan_names), program)
        if scope_tok not in self._evict_reg:
            # serial keys never get overwritten by a reused id, so dead
            # scopes' entries must be evicted explicitly or they leak one
            # compiled executable per scope.  weakref: the finalizer must
            # not keep either the scope or this executor alive.
            self._evict_reg.add(scope_tok)
            try:
                weakref.finalize(scope, _scope_evict_cb,
                                 weakref.ref(self), scope_tok)
            except TypeError:      # non-weakrefable foreign scope-like
                pass
        return self._dispatch(cb, key, feed, scope, program,
                              return_numpy, seed, t0)

    def _dispatch(self, cb, key, feed, scope, program, return_numpy, seed,
                  t0):
        import contextlib
        from .. import profiler as _prof
        ctx = (_prof.RecordEvent("executor.run")
               if _prof.is_profiler_enabled() else contextlib.nullcontext())
        with ctx:
            return self._finish_run(cb, key, feed, scope, program,
                                    return_numpy, seed, t0)

    def _finish_run(self, cb, key, feed, scope, program, return_numpy, seed,
                    t0):
        stats = self._stats
        prog_id = program.fingerprint()[0]
        ts0 = time.perf_counter()
        # verifier-classified programs carry the feeds PROVEN bounded
        # (skip the runtime wrap check for exactly those); every other
        # feed keeps the legacy actual-dtype check — including feeds
        # declared int32/float but fed an int64 array, which the
        # declared-dtype classification cannot see.  None = never
        # verified.  Resolved once per compiled block.
        skip = getattr(cb, "int64_static", _UNSET)
        if skip is _UNSET:
            va = program._attrs.get("verify")
            skip = cb.int64_static = (
                frozenset(va["int64_static"])
                if va is not None and va.get("int64_static") is not None
                else None)
        feeds = [_to_device(feed[n], n, prog_id, skip)
                 for n in cb.feed_names]
        if _monitor.TRACER.enabled and feeds:
            _monitor.TRACER.add_complete(
                "executor.stage_feeds", "dataloader", ts0,
                time.perf_counter())
        ro_vals = [_scope_fetch(scope, n) for n in cb.persist_ro]
        # read-write persistables that are READ must be initialized (optimizer
        # accumulators, BN stats, step counters) — a silent zero would corrupt
        # training state; pure write-before-read vars get dummy zeros since the
        # lowered value never depends on the input.
        rw_vals = []
        for n in cb.persist_rw:
            v = _scope_fetch(scope, n, allow_missing=n not in cb.rw_read)
            rw_vals.append(v if v is not None else jnp.zeros((), jnp.float32))
        # donation-aliasing sanitizer: the jitted step donates the rw
        # buffers, so the SAME jax array under two scope names would be
        # donated twice — a cryptic XLA crash.  Catch it here with names.
        seen_ids = {}
        for n, v in zip(cb.persist_rw, rw_vals):
            if isinstance(v, jax.Array):
                other = seen_ids.setdefault(id(v), n)
                if other is not n:
                    raise ValueError(
                        f"scope vars {other!r} and {n!r} alias the SAME "
                        "device array; the executor donates read-write "
                        "buffers, so aliased scope entries are invalid — "
                        "np.copy() the value when duplicating it")

        try:
            # value-domain fault drill (tools/numerics_smoke.py): the
            # 'numerics.poison' site corrupts one float rw persistable
            # INPUT the way a bf16 overflow inside the step would — an
            # async device op; the poisoned step's OWN stats frame shows
            # the NaN, so the numerics plane (not this hook) detects it
            # and quarantines the step before its capture can commit
            _resil.maybe_inject("numerics.poison")
        except _resil.InjectedFault:
            rw_vals = list(rw_vals)
            for i, v in enumerate(rw_vals):
                if hasattr(v, "dtype") and getattr(v, "ndim", 0) >= 1 \
                        and jnp.issubdtype(v.dtype, jnp.floating):
                    rw_vals[i] = v * jnp.asarray(
                        float("nan"), dtype=v.dtype)
                    break
        comms_note = None
        if cb.collective_nranks or getattr(cb, "partitioned", False):
            # FLAGS_gang_step_barrier: fingerprint-checked gang barrier
            # BEFORE the dispatch — divergent programs refuse here
            # (GangFingerprintError naming both ranks) instead of
            # deadlocking inside the first unpaired collective.  GSPMD-
            # partitioned steps take the same gate: their fingerprint
            # folds mesh shape + PartitionSpecs (+ "#rules=<table>"), so
            # ranks that planner-picked divergent rule tables refuse by
            # table name instead of deadlocking inside XLA's collectives
            self._maybe_step_barrier(cb, program)
        if cb.collective_nranks or getattr(cb, "partitioned", False):
            # collective-launch observability (analysis.comms): the
            # drill site fires first (hang mode makes THIS rank the
            # straggler its peers must attribute), then the plan's byte
            # counters bump and the coordinator timestamp exchange
            # measures peer arrival skew — the straggler-wait half of
            # the decomposition the off-thread monitor completes.
            # GSPMD-partitioned steps share the accounting path (their
            # plan is the reshard projection) but not the drill site:
            # the injection matrix targets explicit collective launches
            if cb.collective_nranks:
                _resil.maybe_inject("collective.launch")
            comms_note = self._comms_prelaunch(cb, program, feeds)
        self._step_seed += 1
        seed_val = seed if seed is not None else (
            program.random_seed * 1000003 + self._step_seed)
        seed_arr = jnp.uint32(seed_val)
        mesh = getattr(cb, "mesh", None)
        if mesh is not None and _mesh_is_multiprocess(mesh):
            # multi-host GSPMD: each process holds its LOCAL slice of the
            # batch and a full copy of host-side state; assemble global
            # arrays before the pjit call (the reference reaches multi-
            # host through NCCL ranks — here through jax.distributed +
            # GSPMD, SURVEY §7's comm-backend design)
            tg0 = time.perf_counter()
            feeds, ro_vals, rw_vals, seed_arr = _to_global_arrays(
                cb, mesh, feeds, ro_vals, rw_vals, seed_arr)
            _COLL_H2G.inc()
            if _monitor.TRACER.enabled:
                _monitor.TRACER.add_complete(
                    "collective.host_to_global", "collective", tg0,
                    time.perf_counter())
        # compile telemetry: a freshly-lowered block pays trace + lower +
        # XLA compile inside its first call (the jit call blocks until the
        # executable exists; only the execution is async).  Record the
        # wall time and whether the persistent disk cache absorbed it —
        # heuristically, by whether the cache dir gained an entry ('hit'
        # also covers compiles under jax's persist threshold).
        pending_compile = getattr(cb, "pending_compile", False)
        if pending_compile:
            # read-and-clear under the lock: a second thread cache-hitting
            # this cb while the first is still inside the compiling call
            # must not record a duplicate compile (its wall time would be
            # time spent blocked behind the real one)
            with self._lock:
                pending_compile = getattr(cb, "pending_compile", False)
                cb.pending_compile = False
        if pending_compile:
            from ..flags import get_flags as _gf
            fl_c = _gf(["FLAGS_xla_compile_cache_dir",
                        "FLAGS_cost_crosscheck"])
            cache_dir = fl_c["FLAGS_xla_compile_cache_dir"]
            n_before = _compile_cache_entries(cache_dir)
            tc0 = time.perf_counter()
            if fl_c["FLAGS_cost_crosscheck"]:
                # AOT-compile so XLA's own cost_analysis() is available
                # to cross-check the analytic model; the compiled object
                # is then USED for execution (same pattern as the
                # RECORD_HBM path), so the check costs no extra compile
                try:
                    compiled = cb.jitted.lower(
                        feeds, ro_vals, rw_vals, seed_arr).compile()
                    cb._compiled_aot = compiled
                    from ..analysis.cost import (xla_cost_breakdown,
                                                 xla_cost_totals)
                    ca = compiled.cost_analysis()
                    cb._xla_cost = xla_cost_totals(ca)
                    cb._xla_breakdown = xla_cost_breakdown(ca)
                except Exception:
                    cb._xla_cost = None
        step_id = next(_GLOBAL_STEPS)
        global _LAST_STEP_ID
        _LAST_STEP_ID = step_id
        try:
            # watchdog: a dispatch (incl. a first-call compile) exceeding
            # FLAGS_watchdog_timeout_s becomes a HungStepError with a
            # stack+telemetry dump instead of an indefinite hang; the
            # injection hook fires INSIDE the watched region so a
            # 'hang'-mode fault exercises exactly that path.  The
            # StepTraceAnnotation stamps the SAME step id onto the
            # device trace (jax.profiler/xprof groups device ops under
            # it), so sampled device windows correlate 1:1 with the
            # host-side executor.dispatch span for the step.
            with _resil.WATCHDOG.watch("executor.dispatch"), \
                    jax.profiler.StepTraceAnnotation(
                        "paddle_tpu.step", step_num=step_id):
                _resil.maybe_inject("executor.dispatch")
                # OOM drill site: an injected fault here runs the SAME
                # forensics path a real RESOURCE_EXHAUSTED from the
                # compile/dispatch below does (tools/hbm_smoke.py)
                _resil.maybe_inject("memory.oom")
                out = cb(feeds, ro_vals, rw_vals, seed_arr)
                if len(out) == 4:
                    fetches, new_rw, probe, num_stats = out
                else:
                    fetches, new_rw, probe = out
                    num_stats = None
        except Exception as e:
            # never cache a block whose trace failed (a later run with a
            # fixed scope/feed must re-lower); drop plans pointing at it
            # too.  Injected faults and watchdog expirations are raised
            # AROUND the call, not by a failed trace — evicting on those
            # would make every recovered fault pay a full re-lower, so
            # resilience drills would measure recompile cost, not
            # recovery cost.
            if not isinstance(e, (_resil.InjectedFault,
                                  _resil.HungStepError)):
                with self._lock:
                    self._cache.pop(key, None)
                    for fk in [k for k, p in self._plans.items()
                               if p.key == key]:
                        self._plans.pop(fk, None)
            from .. import memory as _memory
            injected_oom = getattr(e, "site", None) == "memory.oom"
            if _memory._is_oom_error(e) or injected_oom:
                # an on-chip OOM is a raw XLA error; attach what was
                # actually resident (ref retry_allocator/facade stats
                # surface the same information on CUDA OOM) and write the
                # full forensics dump (paddle_tpu.hbm: static-plan live
                # set at the peak op, budget/plan/measured/requested
                # arithmetic, serving census) — counted in
                # paddle_tpu_oom_total, traced as a memory.oom instant,
                # and it opens a profiler window (trigger:"oom").
                # Neither step must ever mask the OOM itself.
                dump_path = None
                try:
                    dump_path = _hbm().oom_forensics(
                        e, scope=scope, program=program,
                        fetch_names=cb.fetch_names,
                        batch=_feed_batch(feeds),
                        site="injected" if injected_oom else
                        ("compile" if pending_compile else "dispatch"))
                except Exception:
                    pass
                try:
                    report = _memory.summary(scope)
                except Exception:
                    report = "(memory summary unavailable)"
                if dump_path:
                    report += f"\n\noom forensics dump: {dump_path}"
                if injected_oom:
                    # the drill must stay an InjectedFault (transient by
                    # contract — serving retry absorption, resilience
                    # counters); append the forensics in place
                    e.args = ((f"{e.args[0]}\n\n{report}"
                               if e.args else report),)
                    raise
                try:
                    wrapped = type(e)(f"{e}\n\n{report}")
                except Exception:
                    wrapped = RuntimeError(f"{e}\n\n{report}")
                raise wrapped from e
            raise
        tdisp = time.perf_counter()
        if pending_compile:
            outcome = ("off" if not cache_dir else
                       "write" if _compile_cache_entries(cache_dir)
                       > n_before else "hit")
            _COMPILE_CTR.inc(1, persist=outcome)
            _COMPILE_HIST.observe((tdisp - tc0) * 1e3)
            if _monitor.TRACER.enabled:
                _monitor.TRACER.add_complete(
                    "xla.compile", "compile", tc0, tdisp,
                    {"persist_cache": outcome,
                     "fetches": list(cb.fetch_names)})
        if cb.collective_nranks or getattr(cb, "partitioned", False):
            if cb.collective_nranks:
                _COLL_STEP.inc()
            if comms_note is not None:
                # synchronous byte accounting (a lock+add per collective
                # on pre-bound cells — failed dispatches never count, so
                # the counter is exactly plan-bytes x dispatched steps),
                # then hand the step's probe to the comms monitor: it
                # blocks until the step retires OFF this thread and
                # decomposes the wall time into wait vs wire (zero added
                # host blocks on the training thread — the smoke's
                # gate (c))
                plan, cells, t_launch, wait_ms = comms_note
                try:
                    for cell, payload in cells:
                        cell.inc(payload)
                    if not pending_compile:
                        # a compiling first call would bill trace+lower+
                        # XLA-compile seconds as wire time — bytes count
                        # (the launch happened), the timing sample
                        # starts with the first steady-state dispatch
                        _comms().MONITOR.note_launch(
                            step_id, probe, plan, t_launch, tdisp,
                            wait_ms)
                except Exception:
                    pass     # telemetry must never fail a step
        stats.incr("steps_dispatched")
        stats.incr("time_to_dispatch_us", (tdisp - t0) * 1e6)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete("executor.dispatch", "dispatch",
                                         t0, tdisp, {"step": step_id})
        # -- live device-time attribution (analysis.cost) -----------------
        # resolved ONCE per compiled block (fingerprint-cached plan);
        # the steady-state step pays one getattr + a median-window
        # update + two
        # bound-gauge stores — nothing here syncs the device
        cost = getattr(cb, "cost_info", _UNSET)
        if cost is _UNSET:
            cost = cb.cost_info = _resolve_cost(cb, program, feeds)
            xla_cost = getattr(cb, "_xla_cost", None)
            if xla_cost is not None and cost is not None:
                xla_flops = xla_cost[0]
                _XLA_FLOPS_GAUGE.set(xla_flops)
                if xla_flops <= 0:
                    verdict = "unavailable"
                elif cost[2] < 0.5:
                    # MXU-class work (matmul/conv/attention) is where the
                    # two accountings must agree; a program dominated by
                    # elementwise/RNG ops (a startup init, a metrics
                    # pass) diverges legitimately — XLA bills
                    # transcendentals, the analytic model bills elements
                    verdict = "skipped"
                else:
                    ratio = cost[0] / xla_flops
                    verdict = ("ok" if 1.0 / _COST_XCHK_BAND <= ratio
                               <= _COST_XCHK_BAND else "divergent")
                _COST_XCHK_CTR.inc(1, verdict=verdict)
                # per-op-class attribution (not just totals): the XLA
                # utilization/bytes-per-operand breakdown rides the
                # tracer record, and a divergent verdict NAMES the
                # analytic class with the largest flop share — the
                # formula to audit first
                breakdown = getattr(cb, "_xla_breakdown", None) or {}
                share = getattr(cb, "cost_share", None) or {}
                div_class = max(share, key=share.get) if share else \
                    "unknown"
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "cost.crosscheck", "compile",
                        {"analytic_flops": cost[0],
                         "xla_flops": xla_flops, "verdict": verdict,
                         "analytic_share": {k: round(v, 4) for k, v
                                            in share.items()},
                         "xla_breakdown": breakdown,
                         **({"divergent_class": div_class}
                            if verdict == "divergent" else {})})
                if verdict == "divergent":
                    _COST_XCHK_CLASS_CTR.inc(1, op_class=div_class)
                    import warnings
                    util = breakdown.get("operand_utilization", {})
                    warnings.warn(
                        f"analytic cost model reports {cost[0]:.3g} "
                        f"flops/step but XLA cost_analysis() reports "
                        f"{xla_flops:.3g} (>{_COST_XCHK_BAND}x apart); "
                        f"largest analytic share: {div_class} "
                        f"({share.get(div_class, 0.0):.0%}) — audit its "
                        f"formula in analysis/cost.py first (XLA "
                        f"transcendentals="
                        f"{breakdown.get('transcendentals', 0):.3g}, "
                        f"operand utilization={util})")
        # median of the last few inter-dispatch intervals, not an
        # EMA: the first interval after a compile carries warmup
        # noise an EMA would average in for many steps, while the
        # median discards it after two clean steps.  Tracked
        # PER-EXECUTOR, not per compiled block: an executor
        # alternating two blocks (train + eval) would otherwise
        # measure each block's interval across the whole A->B->A
        # cycle and report ~2x the real step time.  Lock-guarded:
        # concurrent run() threads iterate the deque (sorted) while
        # appending.  Computed cost-plan or not: the sampling
        # profiler's regression auto-trigger keys off the same median.
        with self._lock:
            last = self._last_dispatch_t
            self._last_dispatch_t = tdisp
            med = None
            if last is not None and tdisp > last:
                self._step_win.append(tdisp - last)
                med = sorted(self._step_win)[
                    len(self._step_win) // 2]
        if med is not None and cost is not None:
            stats.set_step_timing(med * 1e3,
                                  cost[0] / med / cost[1])
        _maybe_sample_step(step_id,
                           med * 1e3 if med is not None else None)
        # -- numerics observability (analysis.numerics) --------------------
        # the packed stats vector is an in-flight device array: hand it
        # to the engine and poll — ready frames are decoded, pending ones
        # stay lazy (zero host syncs on this thread in steady state)
        if num_stats is not None:
            num_layout = cb.numerics_layout
            if num_layout is None and cb._num_layout_box:
                num_layout = cb.numerics_layout = cb._num_layout_box[0]
            if num_layout is not None:
                _numerics().ENGINE.note_step(step_id, num_stats,
                                             num_layout)
        # batch write-back (async scope plane): one epoch bump per step,
        # values stay in-flight device arrays — scope.find_var readers
        # remain lazy, host consumers call scope.materialize(name)
        wb = dict(zip(cb.persist_rw, new_rw))
        if hasattr(scope, "set_vars"):
            scope.set_vars(wb)
        else:                       # foreign scope-likes (tests, tools)
            for n, v in wb.items():
                scope.set_var(n, v)
        if self._step_hooks:
            # step boundary: scope state is complete for this step (the
            # arrays may still be in flight on device — hooks that need
            # host values must copy device-side and sync elsewhere, the
            # checkpoint daemon's contract)
            for h in list(self._step_hooks):
                h(self, scope)
        # -- runtime HBM accounting (paddle_tpu.hbm) -----------------------
        # one bounded deque append per sampled step: the accountant
        # samples live bytes OFF-thread and joins them against the
        # static plan — zero added host blocks on this thread (the
        # hbm_smoke gate).  After the hooks, so a checkpoint capture's
        # transient copies are attributed to ckpt_capture same-step.
        acc = _hbm().ACCOUNTANT
        if acc.enabled and step_id % acc.every_n == 0:
            info = getattr(cb, "hbm_info", _UNSET)
            if info is _UNSET:
                info = cb.hbm_info = _resolve_hbm_info(cb, program,
                                                       feeds)
            with self._lock:
                infl = sum(int(getattr(a, "nbytes", 0) or 0)
                           for a in self._inflight)
            acc.note_step(step_id, scope, info, infl)
        from ..flags import get_flags
        fl = get_flags(["FLAGS_benchmark",
                        "FLAGS_executor_max_inflight_steps"])
        if fl["FLAGS_benchmark"]:
            # ref FLAGS_benchmark: per-step device sync so wall timing is
            # attributable (normally steps pipeline asynchronously) — this
            # wins over async dispatch, so the throttle never engages
            tb = time.perf_counter()
            for v in list(new_rw) + list(fetches):
                if hasattr(v, "block_until_ready"):
                    v.block_until_ready()
            tb1 = time.perf_counter()
            stats.block("benchmark_sync_us", (tb1 - tb) * 1e6)
            if _monitor.TRACER.enabled:
                _monitor.TRACER.add_complete(
                    "executor.benchmark_sync", "dispatch", tb, tb1)
            # everything queued before the flag flipped is now complete;
            # keeping the probes would only pin their buffers in HBM.
            # All _inflight mutations hold the lock: an unlocked clear()
            # can land between a concurrent _throttle's len-check and
            # popleft and crash it on an emptied deque
            with self._lock:
                self._inflight.clear()
        elif not (return_numpy and fetches):
            # an eager step with fetches fully syncs at materialization
            # below — probing it would only pin its fetch buffers in
            # _inflight after the caller is done with them.  Lazy steps
            # and fetch-less eager loops (which never sync otherwise) do
            # feed the throttle.
            self._throttle(probe, fetches, new_rw,
                           int(fl["FLAGS_executor_max_inflight_steps"]))
        if return_numpy:
            stats.incr("eager_fetch_steps")
            tm = time.perf_counter()
            with _resil.WATCHDOG.watch("fetch.materialize"):
                _resil.maybe_inject("fetch.materialize")
                out = [_fetch_to_numpy(f) for f in fetches]
            if fetches:
                tm1 = time.perf_counter()
                stats.incr("fetch_materializations", len(fetches))
                stats.block("materialize_block_us", (tm1 - tm) * 1e6)
                if _monitor.TRACER.enabled:
                    # step id on the span: tools/latency_report.py chains
                    # executor-only traces (dispatch + materialize) by it
                    _monitor.TRACER.add_complete(
                        "fetch.materialize", "fetch", tm, tm1,
                        {"n": len(fetches), "step": step_id})
                # this step's fetches are on host, and per-device
                # execution is in-order, so every earlier step's probe is
                # complete — retaining them after a lazy→eager switch
                # would pin the lazy phase's fetch buffers in HBM
                with self._lock:
                    self._inflight.clear()
            return out
        stats.incr("lazy_fetch_steps")
        return [FetchHandle(f, stats) for f in fetches]

    def _maybe_step_barrier(self, cb, program):
        """Automatic per-step gang barrier for collective shard_map
        dispatches, behind ``FLAGS_gang_step_barrier``: every step first
        clears the coordinator's fingerprint-enforcing ``step_barrier``
        (socket gang backend), so a rank whose program diverged — a
        different collective sequence, including loop-body collectives
        the block-path-stamped fingerprint now covers — refuses with
        :class:`GangFingerprintError` BEFORE entering the collective.
        Without the flag (default) the runner/tests own the barrier
        cadence, as before PR 7."""
        from ..flags import get_flags
        fl = get_flags(["FLAGS_gang_step_barrier",
                        "FLAGS_gang_step_barrier_timeout_s"])
        if not fl["FLAGS_gang_step_barrier"]:
            return
        gang = self._resolve_gang()
        if gang is None:
            return
        fp = getattr(cb, "gang_fingerprint", _UNSET)
        if fp is _UNSET:
            # the optimized program carries the verifier's block-path-
            # stamped fingerprint in _attrs["verify"] (clone rides it);
            # fall back to a fresh verify for foreign programs
            try:
                from ..analysis.verifier import collective_fingerprint
                fp = collective_fingerprint(program)
            except Exception:
                fp = None
            cb.gang_fingerprint = fp
        self._barrier_step += 1
        gang.step_barrier(
            self._barrier_step, fingerprint=fp,
            timeout_s=float(fl["FLAGS_gang_step_barrier_timeout_s"]))
        _COLL_BARRIER.inc()

    def _resolve_gang(self):
        """Memoized socket-gang client for this process's rank (the PR-6
        liveness plane), or None: no launcher env, the file backend (no
        liveness plane), or single-rank.  ConnectionError propagates —
        a reachable-for-peers coordinator this rank cannot reach is a
        split coordination plane and must fail loud (PR 6)."""
        gang = self._gang
        if gang is _UNSET:
            try:
                from ..distributed.env import GangRendezvous
                gang = GangRendezvous.from_env()
            except ConnectionError:
                raise
            except Exception:
                gang = None
            if gang is not None and not hasattr(gang, "step_barrier"):
                gang = None    # file backend has no liveness plane
            self._gang = gang
        return gang

    def _comms_prelaunch(self, cb, program, feeds):
        """FLAGS_comms_telemetry: per-collective-dispatch observability
        prologue.  Resolves the static comms plan once per compiled
        block, exchanges this rank's arrival timestamp through the gang
        coordinator (``comm_gate`` — the socket-plane timestamp
        allgather), and returns ``(plan, byte_cells, t_launch, wait_ms)``
        for the post-dispatch accounting, or None when telemetry is off
        or the program has no comms plan.  Never raises: telemetry must
        not fail a step."""
        from ..flags import get_flags
        try:
            if not get_flags("FLAGS_comms_telemetry")[
                    "FLAGS_comms_telemetry"]:
                return None
            info = getattr(cb, "comms_info", _UNSET)
            if info is _UNSET:
                info = cb.comms_info = _resolve_comms(cb, program, feeds)
            if info is None:
                return None
            plan, cells = info
            wait_ms = self._comm_gate_wait()
            return plan, cells, time.perf_counter(), wait_ms
        except Exception:
            return None

    def _comm_gate_wait(self):
        """Pre-collective timestamp exchange: post this rank's wall-clock
        arrival to the coordinator's ``comm_gate`` and wait (bounded) for
        every live peer's, returning the straggler wait in ms — how long
        this rank would stall inside the collective for its slowest
        peer.  None when no socket gang is attached (a single-process
        multi-device run: all "ranks" arrive together, wait is 0 by
        construction and the monitor records it as such).  The gate
        latches itself off after 3 consecutive failures so a desynced or
        half-dead gang can never stall training on telemetry."""
        if self._comm_gate_off:
            return None
        gang = None
        try:
            gang = self._resolve_gang()
        except ConnectionError:
            self._comm_gate_off = True     # telemetry never fails a step
            _comms().COMMS_GATE_CTR.inc(1, outcome="disabled")
            return None
        if gang is None or not hasattr(gang, "comm_gate"):
            return None
        from ..flags import get_flags
        timeout_s = float(get_flags("FLAGS_comms_gate_timeout_s")
                          ["FLAGS_comms_gate_timeout_s"])
        # NOTE: arrival timestamps are wall-clock epoch seconds compared
        # ACROSS processes — exact on one host (the current multi-chip
        # deployment); across hosts, NTP skew reads as (or cancels)
        # straggler wait, so cross-host wait decomposition is only as
        # good as the fleet's clock sync (documented in README)
        t_arrive = time.time()
        t0 = time.perf_counter()
        try:
            resp = gang.comm_gate(t_arrive, timeout_s=timeout_s)
        except Exception:
            self._note_gate_failure("error")
            return None
        ts = {int(r): float(t) for r, t in (resp.get("ts") or {}).items()}
        released = bool(resp.get("released"))
        if not released and \
                time.perf_counter() - t0 >= 0.8 * timeout_s:
            # a TIMEOUT-scale partial is a stall this gate itself paid:
            # a peer that stopped posting (its telemetry off, its own
            # gate latched) would otherwise cost every OTHER rank the
            # full timeout on every step — these count toward the
            # self-disarm latch exactly like transport errors.  Fast
            # partials (dead/departed peer: the coordinator returns
            # immediately) cost nothing and don't count.
            self._note_gate_failure("timeout")
            return None
        _comms().COMMS_GATE_CTR.inc(
            1, outcome="released" if released else "partial")
        self._comm_gate_fails = 0
        if not ts:
            return None
        # a fast partial view understates the skew; report what was
        # actually observed rather than guessing
        return max(0.0, (max(ts.values()) - t_arrive) * 1e3)

    def _note_gate_failure(self, kind):
        """Count a comm-gate failure toward the 3-strike self-disarm
        latch (transport errors and timeout-scale stalls alike —
        telemetry must never keep stalling training)."""
        _comms().COMMS_GATE_CTR.inc(1, outcome=kind)
        self._comm_gate_fails += 1
        if self._comm_gate_fails >= 3:
            self._comm_gate_off = True
            import warnings
            warnings.warn(
                "comms telemetry: pre-collective timestamp gate failed "
                f"3 times in a row (last: {kind}); disabling the gate "
                "for this executor (wait decomposition reads 0, wire "
                "measurement continues)")
            _comms().COMMS_GATE_CTR.inc(1, outcome="disabled")

    def _throttle(self, probe, fetches, new_rw, limit):
        """Bound async run-ahead: remember one output array per dispatched
        step and block on the oldest once more than ``limit`` are in
        flight.  The lowered step emits a dedicated tiny probe output
        (never donated, never consumed by later steps, ready only when
        the step's execution completes), so even a fetch-less
        ``train_from_dataset`` loop — whose rw state the next step
        donates — always hands the throttle a waitable array; fetch
        buffers and rw state remain the fallback for foreign compiled
        blocks without one."""
        if not hasattr(probe, "block_until_ready"):
            probe = next((v for v in list(fetches) + list(new_rw)
                          if hasattr(v, "block_until_ready")), None)
        with self._lock:
            if probe is not None:
                self._inflight.append(probe)
            if limit <= 0:                  # throttle disabled
                self._inflight.clear()
                return
        stats = self._stats
        while True:
            # pop under the lock: concurrent run() threads racing the
            # len-check against each other's popleft would land one of
            # them on an emptied deque (block_until_ready below releases
            # the GIL, so the stale-check window is wide)
            with self._lock:
                if len(self._inflight) <= limit:
                    return
                arr = self._inflight.popleft()
            try:
                if not (hasattr(arr, "is_deleted") and arr.is_deleted()):
                    tb = time.perf_counter()
                    arr.block_until_ready()
                    tb1 = time.perf_counter()
                    stats.incr("throttle_waits")
                    stats.block("throttle_block_us", (tb1 - tb) * 1e6)
                    _THROTTLE_HIST.observe((tb1 - tb) * 1e6)
                    if _monitor.TRACER.enabled:
                        _monitor.TRACER.add_complete(
                            "executor.throttle_wait", "dispatch", tb, tb1)
            except Exception:
                # a probe whose buffer a later step donated is legitimately
                # dead (is_deleted above can race the donation) — anything
                # else is a real async device failure first surfacing here,
                # and swallowing it would let the loop keep dispatching
                # steps that depend on a poisoned state.  The buffer's own
                # post-hoc deleted state is the discriminator, not the
                # error text (XLA failure messages can mention donation)
                if not (hasattr(arr, "is_deleted") and arr.is_deleted()):
                    raise

    def drain(self) -> int:
        """Block until every in-flight dispatched step has retired, leaving
        the scope's persistable state fully computed — the preemption
        guard's pre-checkpoint barrier (``PreemptionGuard.drain``), also
        useful before forking or snapshotting externally.  Returns the
        number of steps waited on.  Deleted probes (their buffer donated
        to a later step) are skipped, same as ``_throttle``; a real async
        device failure surfacing here re-raises."""
        with self._lock:
            pending = list(self._inflight)
            self._inflight.clear()
        waited = 0
        for arr in pending:
            try:
                if not (hasattr(arr, "is_deleted") and arr.is_deleted()):
                    arr.block_until_ready()
                    waited += 1
            except Exception:
                if not (hasattr(arr, "is_deleted") and arr.is_deleted()):
                    raise
        return waited

    def infer_from_program(self, *a, **k):
        return self.run(*a, **k)

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           trainer_desc=None):
        """ref ``framework/executor.cc:143`` RunFromDataset + MultiTrainer:
        drain the dataset's slot batches through the training program.

        The steady-state loop is fully asynchronous: batches flow through
        the dataloader's ``_prefetch_to_device`` double buffer (host
        parsing + H2D staging of batch *i+1* overlaps device compute of
        batch *i* — ref ``buffered_reader.cc``'s double-buffer reader),
        steps dispatch with lazy fetches, and fetch/dump values only
        materialize (device→host sync) at ``print_period``/dump-flush
        boundaries instead of every step.  A ``TrainerDesc``
        (trainer_factory API) supplies fetch/print config when passed."""
        if dataset is None:
            raise ValueError("dataset is required")
        dump_fields, dump_file = [], None
        if trainer_desc is not None:
            fetch_list = fetch_list or trainer_desc._fetch_vars
            fetch_info = fetch_info or trainer_desc._fetch_info
            print_period = trainer_desc._print_period
            dump_fields = getattr(trainer_desc, "_dump_fields", [])
            if dump_fields and trainer_desc._dump_fields_path:
                # per-worker dump file (ref DistMultiTrainer dump workers,
                # framework/trainer.h:92: each worker streams tab-separated
                # field values for offline analysis)
                import os
                os.makedirs(trainer_desc._dump_fields_path, exist_ok=True)
                wid = os.environ.get("PADDLE_TRAINER_ID", "0")
                dump_file = open(os.path.join(
                    trainer_desc._dump_fields_path, f"worker_{wid}"), "w")
        fetch_list = fetch_list or []
        n_fetch = len(fetch_list)
        from ..data.dataloader import _prefetch_to_device
        pending_dump = []       # (batch idx, in-flight handles) to flush

        def _flush_dump():
            # one device→host sync per flush window, not per step
            for bi, vals in pending_dump:
                for name, val in zip(dump_fields, vals):
                    flat = " ".join(
                        str(x) for x in np.asarray(val).ravel())
                    dump_file.write(f"{bi}\t{name}\t{flat}\n")
            pending_dump.clear()

        # flush at print_period boundaries, but never hold more than a few
        # batches of un-materialized dump buffers: each pending batch pins
        # len(dump_fields) live fetch arrays in HBM (the in-flight
        # throttle bounds pipelined COMPUTE, not retained buffers), so an
        # uncapped window of print_period=100 large activations would OOM
        # where the old per-step writer streamed them out
        flush_every = max(1, min(int(print_period), 8))
        # a mesh spanning processes assembles global arrays from HOST
        # numpy (_to_global_arrays) — pre-staging would force a D2H pull
        # per feed per step; the prefetch thread then only overlaps
        # parsing, not the H2D copy
        from ..compiler import CompiledProgram
        cp_mesh = (program._mesh
                   if isinstance(program, CompiledProgram) else None)
        stage = not (cp_mesh is not None
                     and _mesh_is_multiprocess(cp_mesh))
        results = None
        try:
            for i, feed in enumerate(_prefetch_to_device(
                    lambda: iter(dataset), capacity=2, stage=stage)):
                results = self.run(
                    program, feed=feed,
                    fetch_list=list(fetch_list) +
                    (list(dump_fields) if dump_file else []),
                    scope=scope, return_numpy=False)
                if dump_file:
                    results, dumped = results[:n_fetch], results[n_fetch:]
                    pending_dump.append((i, dumped))
                    if len(pending_dump) >= flush_every:
                        _flush_dump()
                if debug and fetch_list and i % print_period == 0:
                    info = fetch_info or [
                        f.name if hasattr(f, "name") else str(f)
                        for f in fetch_list]
                    msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                    for n, v in zip(info, results))
                    print(f"[train_from_dataset] batch {i}: {msg}")
        finally:
            if dump_file is not None:
                try:
                    _flush_dump()
                finally:
                    dump_file.close()   # even if flush materialization fails
        if results is not None:
            # materialize the final step's fetches: the return contract is
            # numpy, and this is the loop's ONE mandatory host sync
            results = [np.asarray(r) for r in results]
        # the loop is over — retained throttle probes would pin the last
        # steps' fetch buffers (possibly large dump activations) in HBM
        with self._lock:
            self._inflight.clear()
        return results

    def infer_from_dataset(self, *a, **k):
        return self.train_from_dataset(*a, **k)


def _fetch_to_numpy(f):
    """Fetch → numpy, including multi-process arrays: a fetch stacked over
    a cross-host dp axis spans non-addressable devices, so every process
    allgathers it (ref: each NCCL2 trainer fetches its own loss; here all
    ranks see the global stack, which is strictly more informative)."""
    if isinstance(f, jax.Array) and not f.is_fully_addressable:
        from jax.experimental import multihost_utils
        t0 = time.perf_counter()
        out = np.asarray(multihost_utils.process_allgather(f, tiled=True))
        _COLL_ALLGATHER.inc()
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                "collective.process_allgather", "collective", t0,
                time.perf_counter(), {"shape": list(f.shape)})
        return out
    return np.asarray(f)


def _feed_sig(x):
    """(shape, dtype) of a feed WITHOUT np.asarray — materializing a device
    array per run would force a device→host sync in the hot path."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype))
    a = np.asarray(x)
    return (a.shape, str(a.dtype))


def _mesh_is_multiprocess(mesh) -> bool:
    pi = jax.process_index()
    return any(d.process_index != pi for d in mesh.devices.flat)


def _to_global_arrays(cb, mesh, feeds, ro_vals, rw_vals, seed_arr):
    """Host-local values → global arrays for a mesh spanning processes.

    Feeds follow their partition spec (each host's array is its shard of
    the sharded dims — the standard per-host input pipeline contract);
    replicated state asserts same-shape on every host.  Values that are
    already global (scope state from a previous step) pass through."""
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import PartitionSpec as P

    fsh, rosh, rwsh, ssh = cb.in_shardings

    def conv(v, sharding):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return v                     # already global
        a = np.asarray(v)
        spec = sharding.spec
        if len(spec) > a.ndim:           # dummy zeros for write-only rw
            spec = P()
        return mhu.host_local_array_to_global_array(a, mesh, spec)

    def conv_state(v, sharding):
        # Scope state is host-FULL: every process initialized the whole
        # array (first step) or holds the previous step's global array.
        # For a spec sharding an axis that spans processes (e.g. ZeRO-1
        # accumulators over a cross-host dp axis),
        # host_local_array_to_global_array would treat the full copy as
        # this host's shard and inflate the global dim by the process
        # count — slice each device's shard out of the full copy instead.
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return v                     # already global
        a = np.asarray(v)
        spec = sharding.spec
        if len(spec) > a.ndim or all(ax is None for ax in spec):
            return conv(v, sharding)     # replicated: keep the checked path
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: a[idx])

    return ([conv(v, s) for v, s in zip(feeds, fsh)],
            [conv_state(v, s) for v, s in zip(ro_vals, rosh)],
            [conv_state(v, s) for v, s in zip(rw_vals, rwsh)],
            mhu.host_local_array_to_global_array(
                np.asarray(seed_arr), mesh, P()))


#: sentinel: "cb.int64_dynamic not resolved yet" (None is a real value —
#: it means the program was never verified)
_UNSET = object()

#: (program id, feed name) pairs already spot-checked.  Keyed per program —
#: a bare feed name would let one program's check suppress the int64-wrap
#: warning for a DIFFERENT program reusing the name.  Verified programs
#: bypass this path for feeds the verifier proved bounded (see
#: analysis.verifier._classify_int64_feeds); only verifier-dynamic and
#: never-verified feeds reach the spot-check, once per (program, feed)
#: per process.  Guarded by _checked_int64_lock: dataloader/reader
#: PRODUCER threads add tokens while _drop_stage_tokens iterates — an
#: unguarded set raises 'Set changed size during iteration'.
_checked_int64_feeds = set()  # guarded-by: _checked_int64_lock
_checked_int64_lock = threading.Lock()


def _check_int64_range(x, name, prog_id=None):
    """With x64 off, int64 feeds land in int32 (uint64 in uint32); values
    outside the narrow range would wrap SILENTLY (ops/common.py
    canon_dtype).  Spot-check the FIRST batch per (program, feed name) — a
    one-time host min/max scan, keeping the steady-state dispatch path
    clean."""
    tok = (prog_id, name)
    if (x.dtype in (np.int64, np.uint64) and x.size
            and not jax.config.jax_enable_x64):
        with _checked_int64_lock:
            if tok in _checked_int64_feeds:
                return
            _checked_int64_feeds.add(tok)
        t0 = time.perf_counter()
        lo, hi = int(x.min()), int(x.max())
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                "feed.int64_check", "dataloader", t0, time.perf_counter(),
                {"feed": str(name)})
        bad = (hi >= 2**32) if x.dtype == np.uint64 else \
            (lo < -2**31 or hi >= 2**31)
        if bad:
            import warnings
            narrow = "uint32" if x.dtype == np.uint64 else "int32"
            warnings.warn(
                f"feed {name!r} holds values outside the {narrow} range "
                f"([{lo}, {hi}]); these WRAP on device with x64 disabled — "
                f"set JAX_ENABLE_X64=1 for true 64-bit semantics")


def _to_device(x, name=None, prog_id=None, int64_static=None):
    """``int64_static`` is the verifier's static feed classification: the
    feeds PROVEN bounded by every consumer skip the host min/max scan
    entirely; everything else — verifier-dynamic feeds, feeds the
    classification never saw (e.g. declared int32 but fed an int64
    array), and all feeds of never-verified programs (None) — keeps the
    legacy actual-dtype spot check."""
    if isinstance(x, FetchHandle):
        # a lazy fetch fed back as an input: hand XLA the wrapped device
        # array directly — no host sync, the dependency stays on device
        return x._value
    if isinstance(x, (int, float)):
        return jnp.asarray(x)
    if isinstance(x, np.ndarray):
        if name is not None and (int64_static is None
                                 or name not in int64_static):
            _check_int64_range(x, name, prog_id)
        return jnp.asarray(x)
    return x


def _scope_fetch(scope: Scope, name: str, allow_missing=False):
    v = scope.find_var(name)
    if v is None and not allow_missing and not scope.has_var(name):
        raise KeyError(f"persistable var {name!r} not found in scope — "
                       f"did you run the startup program?")
    return v
