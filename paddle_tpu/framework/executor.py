"""Executor: lowers a whole Program block to ONE jitted XLA computation.

The reference Executor (``framework/executor.cc:173,398-440``) interprets a
block op-by-op, dispatching a C++/CUDA kernel per op and garbage-collecting
dead tensors between ops.  On TPU that per-op dispatch is precisely what you
must NOT do — so this Executor plays the role the reference's nGraph subgraph
engine prototyped (``operators/ngraph/ngraph_engine.cc:249-531``: capture
block → build function → shape-keyed compiled-function cache): the *entire*
block becomes one traced JAX function, jit-compiled by XLA, cached by
(program fingerprint, feed shapes/dtypes, fetch set).

Step signature of the lowered function::

    step(feeds, persist_ro, persist_rw, seed) -> (fetches, new_persist_rw)

``persist_rw`` (params + optimizer state + BN running stats — anything a
block op writes) is donated to XLA so parameter updates alias their input
buffers, matching the reference's in-place optimizer kernels without any
explicit memory pass (ref ``ir/memory_optimize_pass/``— XLA buffer
assignment subsumes it).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import registry
from .core import Block, Operator, Program, Variable, default_main_program
from .scope import Scope, global_scope


class LowerCtx:
    """Per-trace context handed to op lowerings."""

    is_abstract = False

    def __init__(self, seed, mesh=None, is_startup=False, amp=False,
                 collective_axis=None):
        self._seed = seed
        self._key = None  # derived lazily: most ops never need RNG
        self._counter = 0
        self.mesh = mesh
        self.is_startup = is_startup
        self.amp = amp
        # set when the block runs under collective shard_map mode: the mesh
        # axis (or ring_id->axis map) the c_* collective ops reduce over
        self.collective_axis = collective_axis

    def _base_key(self):
        if self._key is None:
            seed = self._seed
            if isinstance(seed, jax.Array) and jax.dtypes.issubdtype(
                    seed.dtype, jax.dtypes.prng_key):
                self._key = seed
            else:
                # rbg: much cheaper per-block random bits on TPU than
                # threefry — dropout RNG was ~40% of a BERT step with the
                # default impl
                self._key = jax.random.key(seed, impl="rbg")
        return self._key

    def rng(self):
        self._counter += 1
        return jax.random.fold_in(self._base_key(), self._counter)

    def rng_tagged(self, tag):
        """Deterministic per-tag stream, independent of trace order: an op
        and its grad op fold the same tag and regenerate IDENTICAL bits, so
        masks are recomputed in backward instead of stored (dropout masks
        were ~15% of a BERT step as HBM traffic).  The extra 0x5EED fold
        keeps the tag stream disjoint from the counter stream above."""
        return jax.random.fold_in(
            jax.random.fold_in(self._base_key(), 0x5EED), tag)


def _seed_to_key(seed):
    if isinstance(seed, jax.Array) and jax.dtypes.issubdtype(seed.dtype, jax.dtypes.prng_key):
        return seed
    return jax.random.key(seed)


class _ExecState:
    """SSA value environment while lowering a block."""

    def __init__(self, values: Dict[str, Any]):
        self.values = values
        self.written: set = set()
        # fwd-output name -> ctx._counter before that op's lowering; lets
        # generic grad ops replay a sampling op's rng stream (see run_op)
        self.rng_marks: Dict[str, int] = {}

    def read(self, block: Block, name: str):
        if name == "" or name is None:
            return None
        if name not in self.values:
            raise KeyError(
                f"op input var {name!r} has no value: not fed, not in scope, "
                f"and not produced by a preceding op")
        return self.values[name]

    def write(self, name: str, value):
        if name == "" or name is None:
            return
        self.values[name] = value
        self.written.add(name)


def run_block(ctx: LowerCtx, block: Block, state: _ExecState) -> None:
    """Trace every op of ``block`` into the surrounding JAX computation.

    This is the hot loop of ref ``executor.cc:432`` — except it runs once at
    trace time, not every step.
    """
    for op in block.ops:
        run_op(ctx, block, op, state)


def _op_context(block, op) -> str:
    """Enforce-style diagnostic context (ref platform/enforce.h — the
    reference enriches every kernel error with op/var context)."""
    parts = [f"op={op.type!r}"]
    for slot, names in op.inputs.items():
        for n in names:
            shape = None
            if n and block.has_var(n):
                shape = block.var(n).shape
            parts.append(f"in {slot}:{n} shape={shape}")
    parts.append(f"outs={[n for ns in op.outputs.values() for n in ns]}")
    return "\n  ".join(parts)


def _sanitize_outputs(op, outs):
    """FLAGS_check_nan_inf at the framework level: bind each float output
    to the producing FLUID op (jax_debug_nans reports XLA ops, which users
    can't map back to their program).  The debug branch only executes on a
    hit, so the clean path pays one reduction per output."""
    import jax
    for slot, vals in outs.items():
        for i, v in enumerate(vals):
            if v is None or not hasattr(v, "dtype") or \
                    not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            bad = ~jnp.all(jnp.isfinite(v))
            jax.lax.cond(
                bad,
                lambda t=op.type, s=slot, j=i: jax.debug.print(
                    "FLAGS_check_nan_inf: non-finite value in output "
                    "{s}[{j}] of op {t}", t=t, s=s, j=j),
                lambda: None)


def run_op(ctx: LowerCtx, block: Block, op: Operator, state: _ExecState) -> None:
    if op.type in ("feed", "fetch"):
        return
    try:
        _run_op_inner(ctx, block, op, state)
    except Exception as e:
        if getattr(e, "_pt_op_context", False):
            raise               # already annotated by the failing inner op
        msg = (f"{type(e).__name__} while lowering op {op.type!r}: {e}\n"
               f"  {_op_context(block, op)}")
        err = RuntimeError(msg)
        err._pt_op_context = True
        raise err from e


def _run_op_inner(ctx, block, op, state) -> None:
    if op.type.endswith("_grad") and not registry.has_op(op.type):
        _run_generic_grad(ctx, block, op, state)
        return
    info = registry.get_op_info(op.type)
    if info.raw:
        info.lower(ctx, block, op, state)
        return
    ins = {slot: [state.read(block, n) for n in names]
           for slot, names in op.inputs.items()}
    if ctx.amp:
        from .. import amp as _amp
        ins = _amp.cast_ins(op.type, ins)
    if info.stateful_rng:
        # remember where the counter stream stood so a generic-vjp grad op
        # can REPLAY the same draws when it retraces this forward (else the
        # backward would differentiate a different sample set — the dropout
        # hand-maker avoids this with its saved mask; every other sampling
        # op goes through here)
        mark = ctx._counter
        for names in op.outputs.values():
            for n in names:
                if n:
                    state.rng_marks[n] = mark
    outs = info.lower(ctx, ins, op.attrs) or {}
    from ..flags import get_flags
    if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
        _sanitize_outputs(op, outs)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if i < len(vals):
                state.write(n, vals[i])


def _run_generic_grad(ctx, block: Block, op: Operator, state: _ExecState):
    ins = {}
    for slot, names in op.inputs.items():
        if slot.startswith("OG$"):
            # an output grad may be absent (output unused downstream)
            ins[slot] = [state.values.get(n) for n in names]
        else:
            ins[slot] = [state.read(block, n) for n in names]
    # NO amp cast here: generic_grad_lower casts INSIDE its vjp closure,
    # which keeps master-weight grads f32 (a pre-cast would differentiate
    # wrt the bf16 copy and round every weight grad)
    mark = None
    finfo = registry._REGISTRY.get(op.attrs.get("__fwd_type__"))
    if finfo is not None and finfo.stateful_rng:
        for slot, names in op.inputs.items():
            if not slot.startswith("OG$"):
                continue
            for gn in names:
                base = gn[:-5] if gn and gn.endswith("@GRAD") else None
                if base is not None and base in state.rng_marks:
                    mark = state.rng_marks[base]
                    break
            if mark is not None:
                break
    if mark is None:
        outs = registry.generic_grad_lower(ctx, ins, op.attrs)
    else:
        # rewind the counter so the vjp's retraced forward draws the SAME
        # randomness the forward op consumed, then restore it
        saved = ctx._counter
        ctx._counter = mark
        try:
            outs = registry.generic_grad_lower(ctx, ins, op.attrs)
        finally:
            ctx._counter = saved
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if n and i < len(vals) and vals[i] is not None:
                state.write(n, vals[i])


class _CompiledBlock:
    """A lowered+jitted block specialized to a feed/fetch/persist signature."""

    def __init__(self, program: Program, block_idx: int,
                 feed_names: Tuple[str, ...], fetch_names: Tuple[str, ...],
                 persist_ro: Tuple[str, ...], persist_rw: Tuple[str, ...],
                 mesh=None, in_shardings=None, donate=True,
                 collective=None, feed_ndims=None):
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.persist_ro = persist_ro
        self.persist_rw = persist_rw
        self.collective_nranks = None
        self._donating = bool(donate and persist_rw)
        block = program.blocks[block_idx]
        amp_on = bool(program._attrs.get("amp", False))

        collective_axis = "dp" if collective else None

        def step(feeds, ro, rw, seed):
            ctx = LowerCtx(seed, mesh=mesh, amp=amp_on,
                           collective_axis=collective_axis)
            values = {}
            values.update(dict(zip(persist_ro, ro)))
            values.update(dict(zip(persist_rw, rw)))
            values.update(dict(zip(feed_names, feeds)))
            state = _ExecState(values)
            run_block(ctx, block, state)
            fetches = [state.values[n] for n in fetch_names]
            new_rw = [state.values[n] for n in persist_rw]
            return fetches, new_rw

        if collective:
            # Collective (multi-process DP) mode — ref §3.3: the whole block
            # becomes one shard_map over the dp axis: per-device compute with
            # explicit c_* collectives, batch feeds sharded on dim 0, params
            # replicated.  Fetches come back stacked per-rank (the reference
            # ParallelExecutor also returns per-device fetch values).
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec as P
            try:
                from jax import shard_map
            except ImportError:  # pragma: no cover
                from jax.experimental.shard_map import shard_map
            nranks = int(collective.get("nranks", 0)) or len(jax.devices())
            devs = jax.devices()
            if nranks > len(devs):
                raise ValueError(
                    f"collective mode needs {nranks} devices, have "
                    f"{len(devs)}")
            self.collective_nranks = nranks
            cmesh = Mesh(np.array(devs[:nranks]), ("dp",))
            # trainable params stay replicated by construction (psum'd
            # grads); other persistables (BN running stats — non-trainable
            # params, metric states) see per-rank batch shards and would
            # diverge — average them across ranks (ints: pmax, they advance
            # identically e.g. step counters)
            def _synced_by_grads(n):
                if not block.has_var(n):
                    return False
                v = block.var(n)
                return getattr(v, "is_parameter", False) and \
                    getattr(v, "trainable", True)
            rw_is_param = [_synced_by_grads(n) for n in persist_rw]

            def sharded_step(feeds, ro, rw, seed):
                # per-rank RNG stream (reference multi-process trainers have
                # independent seeds) — fold in the rank
                seed = seed + lax.axis_index("dp").astype(
                    jnp.uint32) * jnp.uint32(1000003)
                fetches, new_rw = step(feeds, ro, rw, seed)
                synced_rw = []
                for v, is_p in zip(new_rw, rw_is_param):
                    if is_p:
                        synced_rw.append(v)
                    elif jnp.issubdtype(v.dtype, jnp.floating):
                        synced_rw.append(lax.pmean(v, "dp"))
                    else:
                        synced_rw.append(lax.pmax(v, "dp"))
                return [f[None] for f in fetches], synced_rw

            # scalar feeds replicate; batched feeds shard on dim 0
            fspecs = [P("dp") if nd >= 1 else P()
                      for nd in (feed_ndims or [1] * len(feed_names))]
            sm_kwargs = dict(
                mesh=cmesh,
                in_specs=(fspecs, [P()] * len(persist_ro),
                          [P()] * len(persist_rw), P()),
                out_specs=([P("dp")] * len(fetch_names),
                           [P()] * len(persist_rw)))
            try:
                inner = shard_map(sharded_step, check_vma=False, **sm_kwargs)
            except TypeError:  # older jax: the kwarg is check_rep
                inner = shard_map(sharded_step, check_rep=False, **sm_kwargs)
            jkw = {}
            if donate and persist_rw:
                jkw["donate_argnums"] = (2,)
            self.jitted = jax.jit(inner, **jkw)
            return

        kwargs = {}
        if donate and persist_rw:
            kwargs["donate_argnums"] = (2,)
        self.in_shardings = in_shardings     # kept for multi-host feeds
        self.mesh = mesh
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
            # updated state must come back in its declared layout, or the
            # next call's arg shardings mismatch the jit signature
            kwargs["out_shardings"] = (None, list(in_shardings[2]))
        if program._attrs.get("is_distributed") and \
                jax.default_backend() != "cpu":
            # PS trainer programs embed host-RPC send/recv io_callbacks,
            # which the tunneled TPU backend can't service — PS mode is the
            # reference's CPU sparse-workload path (ref §3.4), so pin the
            # step to the host CPU backend
            cpu = jax.devices("cpu")[0]
            # jit rejects device= combined with donation or shardings
            kwargs.pop("donate_argnums", None)
            kwargs.pop("in_shardings", None)
            kwargs.pop("out_shardings", None)
            self.jitted = jax.jit(step, device=cpu, **kwargs)
            return
        self.jitted = jax.jit(step, **kwargs)

    _hbm_recorded = False
    _compiled_aot = None

    def __call__(self, feeds, ro, rw, seed):
        if not self._hbm_recorded and \
                os.environ.get("PADDLE_TPU_RECORD_HBM"):
            # capture the executable's HBM allocation plan (ref
            # allocator_facade stats): device.memory_stats() is unavailable
            # through the axon tunnel, but the AOT-compiled executable's
            # memory_analysis IS the on-chip buffer assignment — arguments
            # + temps + outputs is what the runtime allocates for a step.
            # The AOT object is then used for execution, so recording costs
            # no extra compile.
            self._hbm_recorded = True
            try:
                compiled = self.jitted.lower(feeds, ro, rw, seed).compile()
                from .. import memory as _mem
                _mem.record_hbm_plan(
                    ",".join(self.fetch_names) or "<block>",
                    compiled.memory_analysis())
                self._compiled_aot = compiled
            except Exception:
                pass
        if self._compiled_aot is not None:
            if self._donating:
                # rw buffers are donated: a mid-execution failure leaves
                # them deleted, so a fallback retry would mask the real
                # error with 'Array has been deleted' — just run it
                return self._compiled_aot(feeds, ro, rw, seed)
            try:
                return self._compiled_aot(feeds, ro, rw, seed)
            except Exception:
                self._compiled_aot = None
        return self.jitted(feeds, ro, rw, seed)


def _collect_persistables(program: Program, block: Block, scope: Scope,
                          feed_names) -> Tuple[List[str], List[str], set]:
    """Classify persistable vars referenced by a block into read-only vs
    read-write (written by some op); also return the set of vars whose
    INCOMING value matters — read before any top-level write (startup
    programs init a param then copy it: the copy must not force the param
    to pre-exist in the scope).  Sub-block reads are ALWAYS incoming:
    loop lowerings read every carried var's initial value, so no
    write-before-read exemption applies inside sub-blocks."""
    read, written, incoming = set(), set(), set()

    def visit(b: Block, is_sub: bool):
        for op in b.ops:
            for n in op.input_arg_names():
                read.add(n)
                if is_sub or n not in written:
                    incoming.add(n)
            for v in op.attrs.values():
                if isinstance(v, Block):
                    visit(v, True)
            for n in op.output_arg_names():
                written.add(n)

    visit(block, False)
    ro, rw = [], []
    for name in sorted(read | written):
        if name in feed_names or not name:
            continue
        if not block.has_var(name):
            continue
        v = block.var(name)
        if not v.persistable:
            continue
        (rw if name in written else ro).append(name)
    return ro, rw, incoming


class Executor:
    """ref ``python/paddle/fluid/executor.py:295`` Executor.

    ``place`` is advisory: JAX picks the default backend (TPU when present).
    """

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, _CompiledBlock] = {}
        self._lock = threading.Lock()
        self._step_seed = 0

    def close(self):
        self._cache.clear()

    # -- main entry ----------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            seed: Optional[int] = None):
        from ..compiler import CompiledProgram
        mesh = None
        in_shardings = None
        if isinstance(program, CompiledProgram):
            compiled = program
            program = compiled._optimized(
                tuple(f.name if isinstance(f, Variable) else f
                      for f in (fetch_list or [])))
            mesh = compiled._mesh
            in_shardings = compiled._build_in_shardings
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        feed = feed or {}

        # a pserver program is a blocking host loop, not a jittable block
        # (ref listen_and_serv_op.cc RunImpl blocking in Executor::Run)
        lsv = next((op for op in program.global_block().ops
                    if op.type == "listen_and_serv"), None)
        if lsv is not None:
            from ..distributed import ps as _ps
            return _ps.run_pserver(lsv, scope)
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else f for f in (fetch_list or []))
        feed_names = tuple(sorted(feed))

        block = program.global_block()
        collective = program._attrs.get("collective")
        from ..flags import get_flags
        check_nan = bool(
            get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"])
        # the flag is read at trace time (_run_op_inner) — it must be part
        # of the cache key, or toggling it after a first run is a no-op
        key = (program.fingerprint(), feed_names,
               tuple(_feed_sig(feed[n]) for n in feed_names),
               fetch_names, id(scope), id(mesh), check_nan,
               tuple(sorted(collective.items())) if collective else None)
        with self._lock:
            cb = self._cache.get(key)
            if cb is None:
                ro, rw, read_set = _collect_persistables(
                    program, block, scope, feed_names)
                shardings = None
                if in_shardings is not None:
                    shardings = in_shardings(feed_names, ro, rw)
                cb = _CompiledBlock(
                    program, 0, feed_names, fetch_names,
                    tuple(ro), tuple(rw), mesh=mesh,
                    in_shardings=shardings, collective=collective,
                    feed_ndims=tuple(len(_feed_sig(feed[n])[0])
                                     for n in feed_names))
                cb.rw_read = frozenset(n for n in rw if n in read_set)
                self._cache[key] = cb

        import contextlib
        from .. import profiler as _prof
        ctx = (_prof.RecordEvent("executor.run")
               if _prof.is_profiler_enabled() else contextlib.nullcontext())
        with ctx:
            return self._finish_run(cb, key, feed, scope, program,
                                    return_numpy, seed)

    def _finish_run(self, cb, key, feed, scope, program, return_numpy, seed):
        feeds = [_to_device(feed[n], n) for n in cb.feed_names]
        ro_vals = [_scope_fetch(scope, n) for n in cb.persist_ro]
        # read-write persistables that are READ must be initialized (optimizer
        # accumulators, BN stats, step counters) — a silent zero would corrupt
        # training state; pure write-before-read vars get dummy zeros since the
        # lowered value never depends on the input.
        rw_vals = []
        for n in cb.persist_rw:
            v = _scope_fetch(scope, n, allow_missing=n not in cb.rw_read)
            rw_vals.append(v if v is not None else jnp.zeros((), jnp.float32))
        # donation-aliasing sanitizer: the jitted step donates the rw
        # buffers, so the SAME jax array under two scope names would be
        # donated twice — a cryptic XLA crash.  Catch it here with names.
        seen_ids = {}
        for n, v in zip(cb.persist_rw, rw_vals):
            if isinstance(v, jax.Array):
                other = seen_ids.setdefault(id(v), n)
                if other is not n:
                    raise ValueError(
                        f"scope vars {other!r} and {n!r} alias the SAME "
                        "device array; the executor donates read-write "
                        "buffers, so aliased scope entries are invalid — "
                        "np.copy() the value when duplicating it")

        self._step_seed += 1
        seed_val = seed if seed is not None else (
            program.random_seed * 1000003 + self._step_seed)
        seed_arr = jnp.uint32(seed_val)
        mesh = getattr(cb, "mesh", None)
        if mesh is not None and _mesh_is_multiprocess(mesh):
            # multi-host GSPMD: each process holds its LOCAL slice of the
            # batch and a full copy of host-side state; assemble global
            # arrays before the pjit call (the reference reaches multi-
            # host through NCCL ranks — here through jax.distributed +
            # GSPMD, SURVEY §7's comm-backend design)
            feeds, ro_vals, rw_vals, seed_arr = _to_global_arrays(
                cb, mesh, feeds, ro_vals, rw_vals, seed_arr)
        try:
            fetches, new_rw = cb(feeds, ro_vals, rw_vals, seed_arr)
        except Exception as e:
            # never cache a block whose trace failed (a later run with a
            # fixed scope/feed must re-lower)
            with self._lock:
                self._cache.pop(key, None)
            from .. import memory as _memory
            if _memory._is_oom_error(e):
                # an on-chip OOM is a raw XLA error; attach what was
                # actually resident (ref retry_allocator/facade stats
                # surface the same information on CUDA OOM).  The summary
                # itself must never mask the OOM.
                try:
                    report = _memory.summary(scope)
                except Exception:
                    report = "(memory summary unavailable)"
                try:
                    wrapped = type(e)(f"{e}\n\n{report}")
                except Exception:
                    wrapped = RuntimeError(f"{e}\n\n{report}")
                raise wrapped from e
            raise
        for n, v in zip(cb.persist_rw, new_rw):
            scope.set_var(n, v)
        from ..flags import get_flags
        if get_flags("FLAGS_benchmark")["FLAGS_benchmark"]:
            # ref FLAGS_benchmark: per-step device sync so wall timing is
            # attributable (normally steps pipeline asynchronously)
            for v in list(new_rw) + list(fetches):
                if hasattr(v, "block_until_ready"):
                    v.block_until_ready()
        if return_numpy:
            return [_fetch_to_numpy(f) for f in fetches]
        return list(fetches)

    def infer_from_program(self, *a, **k):
        return self.run(*a, **k)

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           trainer_desc=None):
        """ref ``framework/executor.cc:143`` RunFromDataset + MultiTrainer:
        drain the dataset's slot batches through the training program.
        Threaded file parsing happens in the native data feed; the device
        step itself is one XLA computation, so the reference's
        thread-per-device Hogwild loop maps to a single sequential feed
        loop here.  A ``TrainerDesc`` (trainer_factory API) supplies
        fetch/print config when passed."""
        if dataset is None:
            raise ValueError("dataset is required")
        dump_fields, dump_file = [], None
        if trainer_desc is not None:
            fetch_list = fetch_list or trainer_desc._fetch_vars
            fetch_info = fetch_info or trainer_desc._fetch_info
            print_period = trainer_desc._print_period
            dump_fields = getattr(trainer_desc, "_dump_fields", [])
            if dump_fields and trainer_desc._dump_fields_path:
                # per-worker dump file (ref DistMultiTrainer dump workers,
                # framework/trainer.h:92: each worker streams tab-separated
                # field values for offline analysis)
                import os
                os.makedirs(trainer_desc._dump_fields_path, exist_ok=True)
                wid = os.environ.get("PADDLE_TRAINER_ID", "0")
                dump_file = open(os.path.join(
                    trainer_desc._dump_fields_path, f"worker_{wid}"), "w")
        fetch_list = fetch_list or []
        results = None
        try:
            for i, feed in enumerate(dataset):
                results = self.run(
                    program, feed=feed,
                    fetch_list=list(fetch_list) +
                    (list(dump_fields) if dump_file else []),
                    scope=scope)
                if dump_file:
                    results, dumped = (results[:len(fetch_list)],
                                       results[len(fetch_list):])
                    for name, val in zip(dump_fields, dumped):
                        flat = " ".join(
                            str(x) for x in np.asarray(val).ravel())
                        dump_file.write(f"{i}\t{name}\t{flat}\n")
                if debug and fetch_list and i % print_period == 0:
                    info = fetch_info or [
                        f.name if hasattr(f, "name") else str(f)
                        for f in fetch_list]
                    msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                    for n, v in zip(info, results))
                    print(f"[train_from_dataset] batch {i}: {msg}")
        finally:
            if dump_file is not None:
                dump_file.close()
        return results

    def infer_from_dataset(self, *a, **k):
        return self.train_from_dataset(*a, **k)


def _fetch_to_numpy(f):
    """Fetch → numpy, including multi-process arrays: a fetch stacked over
    a cross-host dp axis spans non-addressable devices, so every process
    allgathers it (ref: each NCCL2 trainer fetches its own loss; here all
    ranks see the global stack, which is strictly more informative)."""
    if isinstance(f, jax.Array) and not f.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            f, tiled=True))
    return np.asarray(f)


def _feed_sig(x):
    """(shape, dtype) of a feed WITHOUT np.asarray — materializing a device
    array per run would force a device→host sync in the hot path."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype))
    a = np.asarray(x)
    return (a.shape, str(a.dtype))


def _mesh_is_multiprocess(mesh) -> bool:
    pi = jax.process_index()
    return any(d.process_index != pi for d in mesh.devices.flat)


def _to_global_arrays(cb, mesh, feeds, ro_vals, rw_vals, seed_arr):
    """Host-local values → global arrays for a mesh spanning processes.

    Feeds follow their partition spec (each host's array is its shard of
    the sharded dims — the standard per-host input pipeline contract);
    replicated state asserts same-shape on every host.  Values that are
    already global (scope state from a previous step) pass through."""
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import PartitionSpec as P

    fsh, rosh, rwsh, ssh = cb.in_shardings

    def conv(v, sharding):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return v                     # already global
        a = np.asarray(v)
        spec = sharding.spec
        if len(spec) > a.ndim:           # dummy zeros for write-only rw
            spec = P()
        return mhu.host_local_array_to_global_array(a, mesh, spec)

    def conv_state(v, sharding):
        # Scope state is host-FULL: every process initialized the whole
        # array (first step) or holds the previous step's global array.
        # For a spec sharding an axis that spans processes (e.g. ZeRO-1
        # accumulators over a cross-host dp axis),
        # host_local_array_to_global_array would treat the full copy as
        # this host's shard and inflate the global dim by the process
        # count — slice each device's shard out of the full copy instead.
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return v                     # already global
        a = np.asarray(v)
        spec = sharding.spec
        if len(spec) > a.ndim or all(ax is None for ax in spec):
            return conv(v, sharding)     # replicated: keep the checked path
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: a[idx])

    return ([conv(v, s) for v, s in zip(feeds, fsh)],
            [conv_state(v, s) for v, s in zip(ro_vals, rosh)],
            [conv_state(v, s) for v, s in zip(rw_vals, rwsh)],
            mhu.host_local_array_to_global_array(
                np.asarray(seed_arr), mesh, P()))


_checked_int64_feeds = set()


def _check_int64_range(x, name):
    """With x64 off, int64 feeds land in int32 (uint64 in uint32); values
    outside the narrow range would wrap SILENTLY (ops/common.py
    canon_dtype).  Spot-check the FIRST batch per feed name — a one-time
    host min/max scan, keeping the steady-state dispatch path clean."""
    if (x.dtype in (np.int64, np.uint64) and x.size
            and name not in _checked_int64_feeds
            and not jax.config.jax_enable_x64):
        _checked_int64_feeds.add(name)
        lo, hi = int(x.min()), int(x.max())
        bad = (hi >= 2**32) if x.dtype == np.uint64 else \
            (lo < -2**31 or hi >= 2**31)
        if bad:
            import warnings
            narrow = "uint32" if x.dtype == np.uint64 else "int32"
            warnings.warn(
                f"feed {name!r} holds values outside the {narrow} range "
                f"([{lo}, {hi}]); these WRAP on device with x64 disabled — "
                f"set JAX_ENABLE_X64=1 for true 64-bit semantics")


def _to_device(x, name=None):
    if isinstance(x, (int, float)):
        return jnp.asarray(x)
    if isinstance(x, np.ndarray):
        if name is not None:
            _check_int64_range(x, name)
        return jnp.asarray(x)
    return x


def _scope_fetch(scope: Scope, name: str, allow_missing=False):
    v = scope.find_var(name)
    if v is None and not allow_missing and not scope.has_var(name):
        raise KeyError(f"persistable var {name!r} not found in scope — "
                       f"did you run the startup program?")
    return v
