"""Device workers (ref ``python/paddle/fluid/device_worker.py:71,96,189``
DeviceWorker/Hogwild/DownpourSGD/Section; C++ counterparts
``framework/device_worker.h:103,175,262``).

On TPU the per-thread Hogwild loop collapses into the jitted block the
executor runs (XLA owns intra-step parallelism), so these classes carry
configuration, not threads: Hogwild configures the plain dataset loop,
DownpourSGD the PS push/pull plane, Section the pipeline engine."""

from __future__ import annotations

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "Section"]


class DeviceWorker:
    """ref device_worker.py DeviceWorker base."""

    def __init__(self):
        self._program = None
        self._infer = False

    def _set_program(self, program):
        self._program = program

    def _set_infer(self, infer):
        self._infer = bool(infer)


class Hogwild(DeviceWorker):
    """ref device_worker.py Hogwild — the default dataset-loop worker."""


class DownpourSGD(DeviceWorker):
    """ref device_worker.py DownpourSGD — PS sparse/dense push-pull worker;
    the transpiled send/recv/distributed_lookup_table ops carry the actual
    communication (paddle_tpu.distributed.ps)."""

    def __init__(self):
        super().__init__()
        self.sparse_tables = []
        self.dense_tables = []


class Section(DeviceWorker):
    """ref device_worker.py Section — pipeline-stage worker; maps to
    paddle_tpu.parallel.pipeline's stage executors."""

    def __init__(self, program_list=None, queue_size=30,
                 sync_steps=1, start_cpu_core_id=0):
        super().__init__()
        self.program_list = program_list or []
        self.queue_size = queue_size
        self.sync_steps = sync_steps
        self.start_cpu_core_id = start_cpu_core_id
