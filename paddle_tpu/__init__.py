"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle
Fluid's capabilities (reference: jhjiangcs/Paddle, see SURVEY.md).

Architecture: a Program/Block/Op IR built by a fluid-style layer DSL;
program-level autodiff (grad-op synthesis); an Executor that lowers whole
blocks into single XLA computations; data/model parallelism via
jax.sharding meshes (GSPMD) instead of NCCL SSA graphs; Pallas kernels for
ops XLA can't fuse (see paddle_tpu.pallas).
"""

from . import ops  # registers all op lowerings
from . import amp, initializer, layers, regularizer  # noqa
from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,  # noqa
                   GradientClipByValue)
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa
from .framework import (Program, Variable, append_backward,  # noqa
                        default_main_program, default_startup_program,
                        global_scope, gradients, program_guard, scope_guard,
                        Scope)
from .framework.executor import Executor  # noqa
from . import optimizer  # noqa
from . import evaluator, metrics, nets  # noqa
from . import contrib  # noqa
from . import incubate  # noqa
from . import average, checkpoint, debugger, install_check, net_drawer  # noqa
from . import flags  # noqa  (FLAGS_* env bootstrap runs at import)
from .flags import get_flags, set_flags  # noqa
from .average import WeightedAverage  # noqa
from . import device_worker, trainer_desc, trainer_factory  # noqa
from . import dygraph  # noqa
from . import io  # noqa
from . import memory  # noqa
from . import native  # noqa
from . import monitor  # noqa  (metrics registry + step tracer)
from . import hbm  # noqa  (runtime HBM accountant + OOM forensics)
from . import resilience  # noqa  (fault injection, retries, preemption)
from . import analysis  # noqa  (program verifier: static checks at optimize time)
from . import serving  # noqa  (multi-tenant continuous-batching server)
from . import profiler  # noqa
from . import data  # noqa
from .data import DataFeeder, DataLoader, PyReader  # noqa
from .data_feed_desc import DataFeedDesc  # noqa
from .async_executor import AsyncExecutor  # noqa
from .data.slot_dataset import DatasetFactory  # noqa
from .io import (load_inference_model, load_params, load_persistables,  # noqa
                 load_vars, save_inference_model, save_params,
                 save_persistables, save_vars)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa


class CPUPlace:
    """ref platform/place.h:37 CPUPlace."""
    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    """The TPU analog of CUDAPlace (ref platform/place.h:26): device ordinal
    within jax.devices()."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Fluid API compat alias: CUDAPlace(n) maps to the n-th accelerator.
CUDAPlace = TPUPlace


def device_count():
    import jax
    return len(jax.devices())


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    import jax
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


__version__ = "0.3.0"
