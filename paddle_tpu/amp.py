"""Automatic mixed precision (SURVEY §5.9; ref
``python/paddle/fluid/contrib/mixed_precision/decorator.py:27,208``,
``fp16_lists.py``, ``fp16_utils.py``).

The reference rewrites the ProgramDesc, inserting cast ops around white/black
listed ops and wrapping the optimizer with (dynamic) loss scaling.  The
TPU-native realization casts at lowering time instead: inputs to
matmul-class ops ("white list") are cast to bf16 as the block is traced, and
numerically-sensitive ops ("black list") are forced to f32.  Master weights
stay f32 in the Scope; XLA fuses the cast pairs away, so the effect is pure
bf16 MXU traffic with f32 accumulation — no loss scaling needed for bf16
(the fp16 dynamic-loss-scaling API is kept for parity and for fp16 policies).
"""

from __future__ import annotations

import jax.numpy as jnp

# ops whose FLOPs dominate and that are bf16-safe (ref fp16_lists.py
# white_list)
WHITE_LIST = {
    "mul", "matmul", "matmul_v2", "conv2d", "depthwise_conv2d", "conv3d",
    "conv2d_transpose", "fc", "bilinear_tensor_product",
}

# numerically-sensitive ops forced to f32 (ref fp16_lists.py black_list).
# Norm/softmax ops are NOT here: their lowerings already compute statistics
# in f32 internally and return the input dtype, which keeps the activation
# stream bf16 (the reference had to blacklist them because its kernels were
# dtype-monomorphic).
BLACK_LIST = {
    "softmax_with_cross_entropy", "softmax_with_cross_entropy_grad",
    "cross_entropy", "cross_entropy2",
    "mean", "reduce_mean", "reduce_sum", "sum", "exp", "log",
    "squared_l2_norm", "l2_normalize", "norm",
    "sigmoid_cross_entropy_with_logits",
    "isfinite", "sqrt", "rsqrt", "pow", "logsumexp",
}

# big elementwise traffic (residual adds, bias adds, activations, dropout):
# cast f32→bf16 ONLY when operating on real activation tensors (ndim≥3) so
# scalar/LR-schedule math keeps full precision.  This keeps the residual
# stream bf16 — HBM bandwidth is the usual TPU bottleneck.
BF16_IF_BIG = {
    "elementwise_add", "elementwise_sub", "elementwise_mul", "dropout",
    "gelu", "relu", "tanh", "sigmoid", "swish", "leaky_relu", "relu6",
    "softmax", "layer_norm", "batch_norm", "group_norm", "scale", "concat",
}

_COMPUTE = jnp.bfloat16
_FLOATS = (jnp.float32, jnp.bfloat16, jnp.float16)

# norm ops carry f32 STATE inputs (running mean/var, scale/bias) that must
# not be rounded to bf16 every step — only the activation slot is cast
_SLOT_RESTRICT = {"batch_norm": {"X"}, "layer_norm": {"X"},
                  "group_norm": {"X"}}

# NOTE: the analysis.fusion targets (fused_dense_act,
# fused_embedding_layer_norm) appear in NO list above on purpose: one
# blanket cast over a fused op would differ from the per-op casts of the
# chain it replaced (e.g. a 2-D bias add stays f32 unfused), so their
# lowerings in ops/fused_ops.py replicate this module's per-stage policy
# internally — keep the three policies in sync when editing the lists.


def _cast_all(ins, target, slots=None):
    out = {}
    for slot, arrs in ins.items():
        if slots is not None and slot not in slots:
            out[slot] = arrs
            continue
        converted = []
        for a in arrs:
            if a is not None and hasattr(a, "dtype") and \
                    a.dtype in _FLOATS and a.dtype != target:
                a = a.astype(target)
            converted.append(a)
        out[slot] = converted
    return out


def cast_ins(op_type: str, ins):
    """Apply the AMP policy to an op's input arrays at trace time."""
    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    if base in WHITE_LIST or op_type in WHITE_LIST:
        return _cast_all(ins, _COMPUTE)
    if base in BLACK_LIST or op_type in BLACK_LIST:
        return _cast_all(ins, jnp.float32)
    if base in BF16_IF_BIG:
        big = any(a is not None and getattr(a, "ndim", 0) >= 3
                  for arrs in ins.values() for a in arrs)
        if big:
            return _cast_all(ins, _COMPUTE, _SLOT_RESTRICT.get(base))
    return ins


def enable(program=None):
    """Turn on bf16 AMP for a program's lowering."""
    from .framework.core import default_main_program
    program = program or default_main_program()
    program._attrs["amp"] = True
    program._bump_version()
    return program


class DynamicLossScaler:
    """Dynamic loss-scaling state machine (ref decorator.py:208
    ``update_loss_scaling``): halve the scale (and SKIP the step) on a
    non-finite gradient, grow it after ``incr_every_n_steps``
    consecutive clean steps.

    What's new here is the observability (this PR's satellite): every
    scale move and every skipped step used to be INVISIBLE — now each
    emits an ``amp.loss_scale`` trace instant in the numerics-anomaly
    record format (``analysis.numerics.record_anomaly``: loss-scale
    events are first-class anomaly records, counted in
    ``paddle_tpu_numerics_anomalies_total{kind}``), the live scale is
    the ``paddle_tpu_amp_scale`` gauge, and skipped steps count in
    ``paddle_tpu_amp_skipped_steps_total`` — a run silently wedged at
    scale 1 with every step skipped is diagnosable from /metrics alone.
    """

    def __init__(self, init_loss_scaling=2 ** 15, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                 decr_ratio=0.8, min_scale=1.0):
        self.scale = float(init_loss_scaling)
        self.incr_every_n_steps = int(incr_every_n_steps)
        self.decr_every_n_nan_or_inf = max(int(decr_every_n_nan_or_inf), 1)
        self.incr_ratio = float(incr_ratio)
        self.decr_ratio = float(decr_ratio)
        self.min_scale = float(min_scale)
        self._good_steps = 0
        self._bad_steps = 0
        self._step = 0
        from . import monitor as _monitor
        self._gauge = _monitor.REGISTRY.gauge(
            "paddle_tpu_amp_scale",
            "current dynamic loss scale (fp16 AMP); a scale pinned at "
            "its minimum with skipped steps climbing means the model "
            "is producing non-finite grads every step")
        self._skip_ctr = _monitor.REGISTRY.counter(
            "paddle_tpu_amp_skipped_steps_total",
            "optimizer steps SKIPPED by dynamic loss scaling "
            "(non-finite gradients at the current scale)")
        self._gauge.set(self.scale)

    def _event(self, kind, value=None, detail=None):
        from .analysis import numerics as _numerics
        _numerics.record_anomaly(
            kind, step=self._step, value=value,
            detail=dict(detail or (), scale=self.scale),
            instant="amp.loss_scale")

    def update(self, found_inf) -> bool:
        """Feed one step's found-non-finite verdict; returns True when
        the step's update should be APPLIED, False when it must be
        skipped (grads were non-finite at the current scale)."""
        self._step += 1
        if bool(found_inf):
            self._good_steps = 0
            self._bad_steps += 1
            self._skip_ctr.inc()
            if self._bad_steps >= self.decr_every_n_nan_or_inf:
                self._bad_steps = 0
                old = self.scale
                self.scale = max(self.scale * self.decr_ratio,
                                 self.min_scale)
                self._gauge.set(self.scale)
                self._event("loss_scale_decreased", value=self.scale,
                            detail={"from": old})
            else:
                self._event("step_skipped", value=self.scale)
            return False
        self._bad_steps = 0
        self._good_steps += 1
        if self._good_steps >= self.incr_every_n_steps:
            self._good_steps = 0
            old = self.scale
            self.scale = self.scale * self.incr_ratio
            self._gauge.set(self.scale)
            self._event("loss_scale_increased", value=self.scale,
                        detail={"from": old})
        return True


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True):
    """ref decorator.py:27 — returns an optimizer whose minimize() enables
    bf16 AMP on the program.  bf16 needs no loss scaling (unlike the
    reference's fp16) so the lowering never applies the scale, but the
    scaler STATE MACHINE is real (``.loss_scaler``): fp16-policy callers
    drive it with per-step found-inf verdicts and get the skip/halve/
    grow protocol plus its telemetry (``amp.loss_scale`` instants,
    ``paddle_tpu_amp_scale`` gauge, skipped-step counter)."""

    class _AmpOptimizer:
        def __init__(self, inner):
            self._inner = inner
            self.loss_scaler = (
                DynamicLossScaler(
                    init_loss_scaling=init_loss_scaling,
                    incr_every_n_steps=incr_every_n_steps,
                    decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
                    incr_ratio=incr_ratio, decr_ratio=decr_ratio)
                if use_dynamic_loss_scaling else None)

        @property
        def _loss_scaling(self):
            return (self.loss_scaler.scale
                    if self.loss_scaler is not None
                    else float(init_loss_scaling))

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def minimize(self, loss, **kw):
            enable(loss.block.program)
            return self._inner.minimize(loss, **kw)

        def backward(self, loss, **kw):
            enable(loss.block.program)
            return self._inner.backward(loss, **kw)

    return _AmpOptimizer(optimizer)
