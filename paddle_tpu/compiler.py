"""CompiledProgram: data-parallel execution via GSPMD over a device mesh.

ref ``python/paddle/fluid/compiler.py:65,143`` (CompiledProgram.
with_data_parallel → C++ ParallelExecutor).  The TPU-native realization
replaces the whole SSA-graph machinery (MultiDevSSAGraphBuilder +
AllReduceOpHandle + FastThreadedSSAGraphExecutor,
``framework/details/``, ``ir/multi_devices_graph_pass/``) with sharding
annotations: feeds are sharded along the batch axis of a 1-D ``dp`` mesh,
parameters are replicated, and XLA's SPMD partitioner inserts the gradient
all-reduce (≈ ``CreateAllReduceOp``, multi_devices_graph_pass.cc:454) over
ICI.  Gradient coalescing (ref ``coalesce_grad_tensor_pass``) is XLA's
all-reduce combiner; loss scaling 1/N (ref ``ScaleLossGradOpHandle``) is
unnecessary because the mean over the global batch already spans devices.
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import monitor as _monitor
from .framework.core import Program

_OPT_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_compiler_optimize_total",
    "CompiledProgram graph-pass applications by program-cache outcome",
    ("cache",))
#: bound once: the hit side runs on every steady-state dispatch
_OPT_HIT = _OPT_CTR.labels(cache="hit")
_OPT_MISS = _OPT_CTR.labels(cache="miss")
#: per-pass lowering-time attribution: each optimize-time stage
#: (program verify, dead-op eliminate, fusion, graph->program) observes
#: its wall ms here, and the compiler.optimize span carries the same
#: numbers in its args — so a slow compile names the pass that ate it
_PASS_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_compiler_pass_ms",
    "per-pass wall time (ms) inside compiler.optimize, by pass",
    ("pass",),
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
             250.0, 500.0, 1000.0, 5000.0))

#: monotonic CompiledProgram identity — the executor's compiled-block
#: cache keys on this serial: structurally-equal meshes from two
#: differently-configured CompiledPrograms (different in_shardings /
#: zero stage / input specs) must NOT share a compiled entry, and raw
#: id() can be reused after GC
_cp_serials = itertools.count()


class BuildStrategy:
    """ref details/build_strategy.h — accepted for API parity; the knobs that
    matter on TPU (fusion, coalescing, memory opt) are XLA's job."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.num_trainers = 1
        self.trainer_id = 0
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.sync_batch_norm = False
        self._init_done = True

    # fusion/memory knobs are XLA's job — flipping them changes nothing,
    # which a porting user deserves to hear once (VERDICT r1 weak #7)
    _NOOP_KNOBS = ("fuse_all_reduce_ops", "fuse_all_optimizer_ops",
                   "fuse_elewise_add_act_ops", "memory_optimize",
                   "enable_inplace")

    def __setattr__(self, name, value):
        if getattr(self, "_init_done", False) and name in self._NOOP_KNOBS:
            from .flags import warn_noop
            warn_noop(f"BuildStrategy.{name}",
                      "XLA owns fusion and buffer assignment")
        object.__setattr__(self, name, value)


class ExecutionStrategy:
    """ref details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False
        self._init_done = True

    _NOOP_KNOBS = ("num_threads", "num_iteration_per_drop_scope",
                   "num_iteration_per_run", "use_thread_barrier")

    def __setattr__(self, name, value):
        if getattr(self, "_init_done", False) and name in self._NOOP_KNOBS:
            from .flags import warn_noop
            warn_noop(f"ExecutionStrategy.{name}",
                      "XLA schedules the whole-block computation")
        object.__setattr__(self, name, value)


@contextlib.contextmanager
def _timed_pass(pass_ms: dict, pass_name: str):
    """Per-pass lowering-time attribution: a ``compiler.pass.<name>``
    child span, the pass histogram observation, and the wall ms
    recorded into ``pass_ms`` (attached to the enclosing
    compiler.optimize span's args)."""
    import time as _time
    t0 = _time.perf_counter()
    try:
        yield
    finally:
        t1 = _time.perf_counter()
        ms = (t1 - t0) * 1e3
        pass_ms[pass_name] = round(ms, 3)
        _PASS_HIST.observe(ms, **{"pass": pass_name})
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                f"compiler.pass.{pass_name}", "compile", t0, t1)


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None):
        self._program: Program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._mesh: Optional[Mesh] = None
        self._loss_name = None
        self._share_vars_from = None
        self._is_data_parallel = False
        self._serial = next(_cp_serials)

    def _optimized(self, fetch_names=(), feed_shapes=None) -> Program:
        """Apply the BuildStrategy's graph passes (ref BuildStrategy::Apply,
        details/build_strategy.cc:299 — there the pass list builds the whole
        multi-device graph; here the program-level canonicalizations plus
        the cost-guided fusion pass, XLA owns the rest).  Keyed by program
        version + fetch set + fusion config + feed batch: fetched
        intermediates must survive fusion, a mutated program must
        re-optimize, and a fusion-flag flip (or a batch change, which
        re-ranks/re-tunes candidates) must not reuse a stale rewrite."""
        from .analysis import fusion as _fusion
        batch = _fusion._batch_of(feed_shapes)
        # the partition stamp lives in _attrs, outside the structural
        # fingerprint: a re-applied rule table (apply_rules without a
        # fresh with_gspmd) must re-verify/re-optimize, not reuse the
        # old table's program
        ptok = None
        if self._program._attrs.get("partition"):
            from .parallel.partitioner import partition_fingerprint
            ptok = partition_fingerprint(
                self._program._attrs["partition"])
        key = (self._program.fingerprint(), frozenset(fetch_names),
               _fusion.config_token(), batch, ptok)
        cache = getattr(self, "_optimized_cache", None)
        if cache is None:
            cache = self._optimized_cache = {}
        prog = cache.get(key)
        if prog is None:
            from . import resilience as _resil
            _OPT_MISS.inc()

            def _build():
                # 'compile' injection site + transient-failure retries:
                # only faults marked transient (injected flakes, infra
                # hiccups tagged via mark_transient) earn a retry — a
                # real lowering error is deterministic, and re-running it
                # would just triple the time to the same diagnosis
                _resil.maybe_inject("compile")
                import functools
                import time as _time
                t_opt0 = _time.perf_counter()
                pass_ms = {}
                _timed = functools.partial(_timed_pass, pass_ms)
                try:
                    from .flags import get_flags
                    prog = self._program
                    if get_flags("FLAGS_program_verify")[
                            "FLAGS_program_verify"]:
                        # static analysis BEFORE any pass touches the
                        # graph: defects report against the program the
                        # user built, errors raise here instead of
                        # surfacing mid-trace (or as a cross-rank hang).
                        # ProgramVerificationError is deterministic, so
                        # the transient-only retry policy never re-runs
                        # it.  Also stamps prog._attrs["verify"] (int64
                        # feed classification, collective fingerprint,
                        # analytic cost), which clone() carries onto the
                        # optimized program below.
                        from .analysis import verifier as _verifier
                        with _timed("program_verify"):
                            _verifier.verify_or_raise(prog, fetch_names)
                    from .framework import ir
                    g = ir.Graph(prog)
                    changed = False
                    # dead-op elimination before lowering: never trace a
                    # subgraph nothing observes (fetches are protected)
                    with _timed("dead_op_eliminate"):
                        g = ir.get_pass(
                            "dead_op_eliminate",
                            protected=frozenset(fetch_names)).apply(g)
                    changed |= bool(g.attrs.get("dead_op_eliminate_count"))
                    if changed:
                        with _timed("to_program"):
                            prog = g.to_program()
                        changed = False
                    # cost-guided fusion BEFORE fuse_elewise_add_act,
                    # which would otherwise consume the bias+act tails
                    # the dense-epilogue pattern targets (program-level:
                    # the pass verifies before/after and re-ranks by the
                    # cost model at the real feed batch)
                    with _timed("graph_fusion"):
                        prog = _fusion.fuse_program(
                            prog, fetch_names, feed_shapes=feed_shapes)
                    g = ir.Graph(prog)
                    if self._build_strategy.fuse_elewise_add_act_ops:
                        with _timed("fuse_elewise_add_act"):
                            g = ir.get_pass(
                                "fuse_elewise_add_act_pass",
                                protected=frozenset(fetch_names)).apply(g)
                        changed |= bool(
                            g.attrs.get("fuse_elewise_add_act_count"))
                    if changed:
                        with _timed("to_program"):
                            prog = g.to_program()
                    from .analysis import numerics as _numerics
                    if _numerics.mode() != "off":
                        # stat-capture slot AFTER fusion: the numerics
                        # census must see the vars the REWRITTEN
                        # program actually produces (fused grad names),
                        # not the pre-fusion chain it replaced.
                        # Advisory stamp — the trace-time builder
                        # intersects it with the live value env.
                        with _timed("numerics_spec"):
                            prog._attrs["numerics"] = \
                                _numerics.plan_numerics(prog, fetch_names)
                    return prog
                finally:
                    if _monitor.TRACER.enabled:
                        _monitor.TRACER.add_complete(
                            "compiler.optimize", "compile", t_opt0,
                            _time.perf_counter(),
                            {"fetches": len(fetch_names),
                             "passes_ms": dict(pass_ms)})

            prog = _resil.retry_call("compile", _build,
                                     retryable=_resil.is_transient)
            cache[key] = prog
        else:
            _OPT_HIT.inc()
        return prog

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Shard the batch over every visible device (or ``places``)."""
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._share_vars_from = share_vars_from
        from .parallel.mesh import make_mesh
        devices = None
        if places:
            if isinstance(places, int):
                devices = jax.devices()[:places]
            elif hasattr(places[0], "platform"):   # jax Device objects
                devices = list(places)
        if devices is None:
            devices = jax.devices()
        self._mesh = make_mesh({"dp": len(devices)}, devices)
        # reconfiguration changes what the executor must lower (mesh,
        # shardings) without touching the program fingerprint — a new
        # serial invalidates any compiled block cached for the old config
        self._serial = next(_cp_serials)
        return self

    def with_distributed(self, mesh=None, axes=None, input_specs=None,
                         zero_stage=0):
        """General SPMD: shard params by their ``dist_spec`` annotations and
        feeds by ``input_specs`` (default: batch axis on 'dp') over an
        explicit mesh — dp/tp/sp in one jit, XLA inserts the collectives.
        This is the capability jump over the reference, whose multi-device
        pass only replicated (AllReduce) or row-sharded (Reduce) params.

        ``zero_stage=1`` additionally shards OPTIMIZER STATE over the dp
        axis (ZeRO-1): accumulators whose leading dim divides the dp size
        live partitioned in the scope between steps, cutting per-device
        optimizer memory by the dp degree; GSPMD inserts the
        gather/scatter around the update."""
        from .parallel.mesh import make_mesh
        self._is_data_parallel = True
        if mesh is None and axes is None:
            raise ValueError(
                "with_distributed() needs either `mesh` (a jax.sharding.Mesh)"
                " or `axes` (e.g. {'dp': 2, 'mp': 4})")
        self._mesh = mesh if mesh is not None else make_mesh(axes)
        self._input_specs = dict(input_specs or {})
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1 (ZeRO-1: "
                             "optimizer-state sharding)")
        self._zero_stage = int(zero_stage)
        # see with_data_parallel: a reconfigured mesh/specs/zero stage
        # must not hit blocks compiled for the previous configuration
        self._serial = next(_cp_serials)
        return self

    def with_gspmd(self, axes=None, mesh=None, rules=None,
                   zero_stage=1, input_specs=None, fetch_names=(),
                   batch_size: int = 1, budget_mb=None):
        """Model parallelism via the logical-axis partitioner
        (``parallel.partitioner``): infer each parameter's logical axes
        from the op graph, apply a ``LogicalAxisRules`` table —
        ``rules="auto"`` lets the static HBM planner pick the cheapest
        table whose PER-SHARD peak fits ``FLAGS_memory_budget_mb``
        (``budget_mb`` overrides) — and lower through pjit over a
        hardware-topology mesh.  ZeRO-1 optimizer-state sharding is ON
        by default (``zero_stage=1``); the partition stamp lands in
        ``program._attrs["partition"]`` where the verifier folds it into
        the cross-rank collective fingerprint and the executor applies
        activation sharding constraints.

        ``rules`` accepts a table name (``"replicated"``, ``"mp_hidden"``,
        ``"mp_hidden_vocab"``), a ``{logical_axis: mesh_axis}`` dict, a
        ``LogicalAxisRules``, or ``"auto"``; None reads
        ``FLAGS_gspmd_rules``."""
        from .parallel.mesh import make_topology_mesh, mesh_axis_sizes
        from .parallel import partitioner as _part
        from .flags import get_flags
        self._is_data_parallel = True
        if rules is None:
            rules = get_flags("FLAGS_gspmd_rules")["FLAGS_gspmd_rules"]
        if mesh is None:
            if axes is None:
                spec = get_flags("FLAGS_gspmd_mesh")["FLAGS_gspmd_mesh"]
                if spec:
                    axes = {k: int(v) for k, v in
                            (kv.split(":") for kv in spec.split(","))}
                else:
                    axes = {"dp": 1, "mp": len(jax.devices())}
            mesh = make_topology_mesh(axes)
        self._mesh = mesh
        axis_sizes = mesh_axis_sizes(mesh)
        fetch_names = tuple(
            f.name if hasattr(f, "name") else f for f in fetch_names)
        stamp = _part.partition_program(
            self._program, axis_sizes, rules=rules,
            fetch_names=fetch_names, batch_size=batch_size,
            budget_mb=budget_mb)
        self._partition = stamp
        self._input_specs = dict(input_specs or {})
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1 (ZeRO-1: "
                             "optimizer-state sharding)")
        self._zero_stage = int(zero_stage)
        # the sharding analysis prices ZeRO-1's reduce-scatter/
        # all-gather split off the stamp, and the partition fingerprint
        # hashes it: ranks disagreeing on zero_stage must refuse
        stamp["zero_stage"] = self._zero_stage
        # partition attrs change the verify stamp: drop any verify/plan
        # cached for the pre-partition program, then take a new serial
        # so the executor re-lowers under the new shardings
        self._program._attrs.pop("verify", None)
        self._optimized_cache = {}
        self._serial = next(_cp_serials)
        return self

    def _build_in_shardings(self, feed_names, ro, rw):
        """Sharding pytree for the jitted step(feeds, ro, rw, seed)."""
        if self._mesh is None:
            return None
        from .parallel.mesh import sharding_for
        mesh = self._mesh
        block = self._program.global_block()
        input_specs = getattr(self, "_input_specs", {})

        def feed_shard(name):
            if name in input_specs:
                return sharding_for(mesh, input_specs[name])
            if "dp" in mesh.axis_names:
                return NamedSharding(mesh, P("dp"))
            return NamedSharding(mesh, P())

        zero = getattr(self, "_zero_stage", 0)
        dp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("dp", 1)

        def var_shard(name):
            if not block.has_var(name):
                return NamedSharding(mesh, P())
            v = block.var(name)
            spec = v.dist_spec
            # optimizer accumulators inherit their parameter's layout,
            # resolved here so late TP annotation still applies
            link = getattr(v, "shard_like", None)
            is_acc = bool(link and block.has_var(link))
            if spec is None and is_acc:
                p = block.var(link)
                if tuple(v.shape or ()) == tuple(p.shape or ()):
                    spec = p.dist_spec
            # ZeRO-1: optimizer state additionally partitions its leading
            # dim over dp (when free and divisible) — the state lives
            # sharded in the scope across steps
            if zero and is_acc and dp_size > 1:
                shape = tuple(v.shape or ())
                cur = list(spec) if spec is not None else \
                    [None] * len(shape)
                if (shape and len(cur) == len(shape) and cur
                        and cur[0] is None and shape[0] is not None
                        and shape[0] % dp_size == 0):
                    cur[0] = "dp"
                    spec = tuple(cur)
            return sharding_for(mesh, spec)

        return ([feed_shard(n) for n in feed_names],
                [var_shard(n) for n in ro],
                [var_shard(n) for n in rw],
                NamedSharding(mesh, P()))

    @property
    def program(self):
        return self._program
