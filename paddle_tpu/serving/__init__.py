"""Production inference serving (ROADMAP: the "heavy traffic" half of the
north star).

The reference stack ships a standalone inference engine
(``inference/api/analysis_predictor.h``) but no server; this package turns
the training runtime's substrate — persistent XLA compile cache, async
executor in-flight throttle, per-series telemetry with retirement, static
HBM planning, fault-injection absorption, preemption drain — into a
latency-governed multi-tenant request path:

- :mod:`bucketing` — TVM-style compile buckets: arbitrary request shapes
  pad onto a small fixed set, one XLA executable per bucket, persisted
  across restarts.
- :mod:`scheduler` — continuous batching: coalesce queued requests into
  the widest same-bucket batch, dispatch through the executor's lazy-fetch
  path, absorb transient dispatch faults.
- :mod:`kv_cache` — donated paged KV cache + the single compiled
  ``gpt_causal`` decode step; requests join/leave the slot batch between
  iterations with zero recompiles.
- :mod:`server` — the tenant plane (quotas, per-tenant telemetry with
  retirement) and SIGTERM graceful drain.
- :mod:`slo` — per-tenant objectives (``FLAGS_serving_slo``) evaluated
  with fast/slow multi-window burn-rate math; breaches are trace
  instants, gauges, and (optionally) an admission shed signal.
- :mod:`httpd` — the live scrape surface: ``/metrics`` ``/healthz``
  ``/statusz`` on ``FLAGS_metrics_port``.
- :mod:`fleet` — the multi-replica front door: ``ReplicaEndpoint``
  fronts one server over the gang frame protocol, ``FleetRouter``
  places each request on the least-loaded fresh replica and re-routes
  around drains, deaths, and open breakers (README "Fleet").
- :mod:`autoscaler` — the closed loop that makes the fleet
  self-driving: SLO burn + queue pressure spawn replicas through the
  launcher, sustained idle drains-then-retires, OOM-risk headroom runs
  the per-replica degradation ladder, and breach hysteresis arbitrates
  shed-vs-scale (README "Fleet" → "Autoscaler runbook").

Every request carries a trace id from admission through queueing,
batch coalescing, dispatch (correlated with the executor's process-
global step id), and fetch materialization — the phase spans partition
submit→resolve, so ``tools/latency_report.py`` decomposes p99 by phase
per tenant and bucket from the exported trace ring.
"""

from .autoscaler import AutoscalerPolicy, FleetAutoscaler  # noqa
from .bucketing import BucketPlan, bucket_for, pad_to_bucket, parse_buckets  # noqa
from .fleet import FleetError, FleetRouter, ReplicaEndpoint  # noqa
from .httpd import MetricsHTTPServer  # noqa
from .kv_cache import (DecodeEngine, GPTDecodeModel, PagedKVCache,  # noqa
                       params_from_scope)
from .scheduler import (ContinuousBatcher, DecodeScheduler, Request,  # noqa
                        ServingFuture)
from .server import (AdmissionError, DecodeServer, InferenceServer,  # noqa
                     TenantPlane)
from .slo import BurnRateEvaluator, SLOTarget, parse_slo  # noqa
