"""Production inference serving (ROADMAP: the "heavy traffic" half of the
north star).

The reference stack ships a standalone inference engine
(``inference/api/analysis_predictor.h``) but no server; this package turns
the training runtime's substrate — persistent XLA compile cache, async
executor in-flight throttle, per-series telemetry with retirement, static
HBM planning, fault-injection absorption, preemption drain — into a
latency-governed multi-tenant request path:

- :mod:`bucketing` — TVM-style compile buckets: arbitrary request shapes
  pad onto a small fixed set, one XLA executable per bucket, persisted
  across restarts.
- :mod:`scheduler` — continuous batching: coalesce queued requests into
  the widest same-bucket batch, dispatch through the executor's lazy-fetch
  path, absorb transient dispatch faults.
- :mod:`kv_cache` — donated paged KV cache + the single compiled
  ``gpt_causal`` decode step; requests join/leave the slot batch between
  iterations with zero recompiles.
- :mod:`server` — the tenant plane (quotas, per-tenant telemetry with
  retirement) and SIGTERM graceful drain.
"""

from .bucketing import BucketPlan, bucket_for, pad_to_bucket, parse_buckets  # noqa
from .kv_cache import (DecodeEngine, GPTDecodeModel, PagedKVCache,  # noqa
                       params_from_scope)
from .scheduler import (ContinuousBatcher, DecodeScheduler, Request,  # noqa
                        ServingFuture)
from .server import (AdmissionError, DecodeServer, InferenceServer,  # noqa
                     TenantPlane)
