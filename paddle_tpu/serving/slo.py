"""Per-tenant SLO objectives + multi-window burn-rate evaluation.

``FLAGS_serving_slo`` declares objectives per tenant, e.g.::

    tenantA:p99_ms=250,avail=99.9;tenantB:avail=99;*:p99_ms=500

- ``p99_ms`` — latency objective: a completed request slower than this
  is a BAD event (the "99" is the objective percentile: with no explicit
  ``avail``, the good-fraction objective defaults to 99.0%).
- ``avail`` — good-fraction objective in percent; a failed request is
  always bad.  The error budget is ``1 - avail/100``.
- ``*`` — default target for any tenant without an explicit entry.

Burn rate is the SRE multi-window form: over each of a FAST and a SLOW
trailing window, ``burn = bad_fraction / budget`` — 1.0 consumes the
budget exactly at the allowed rate.  A tenant is IN BREACH when the burn
exceeds ``FLAGS_serving_slo_burn_threshold`` on BOTH windows (the slow
window keeps a blip from paging; the fast window keeps a real fire from
waiting), and RECOVERS with hysteresis when the fast-window burn falls
under half the threshold.  Breach and recovery are recorded as trace
instants (``slo.breach`` / ``slo.recover``) and the live state feeds the
``paddle_tpu_slo_burn_rate{tenant,window}`` / ``paddle_tpu_slo_breached``
gauges plus the optional shed-on-burn admission mode
(``FLAGS_serving_slo_shed``).

Zero-traffic tenants burn nothing: an empty window is burn 0, never a
breach (an idle tenant's SLO is trivially met).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import monitor as _monitor

__all__ = ["SLOTarget", "parse_slo", "BurnRateEvaluator"]

#: events kept per tenant at most — a bound against a window so long or
#: traffic so hot that the ring outgrows memory (oldest dropped; the
#: burn math then sees a shorter effective window, never a crash)
MAX_EVENTS_PER_TENANT = 100_000


class SLOTarget:
    """One tenant's objectives (latency and/or availability)."""

    __slots__ = ("p99_ms", "avail")

    def __init__(self, p99_ms: Optional[float] = None,
                 avail: Optional[float] = None):
        self.p99_ms = None if p99_ms is None else float(p99_ms)
        # the good-fraction objective: explicit avail, else the "99" of
        # p99 — a pure latency target budgets 1% of requests over it
        self.avail = float(avail) if avail is not None else 99.0

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction (floored: avail=100 would make
        any single bad event an infinite burn — clamp keeps it finite
        and still enormous)."""
        return max(1.0 - self.avail / 100.0, 1e-9)

    def is_bad(self, ok: bool, latency_ms: float) -> bool:
        if not ok:
            return True
        return self.p99_ms is not None and latency_ms > self.p99_ms

    def as_dict(self) -> Dict[str, Any]:
        return {"p99_ms": self.p99_ms, "avail": self.avail}


def parse_slo(spec: str) -> Dict[str, SLOTarget]:
    """``FLAGS_serving_slo`` grammar (see module docstring); raises
    ``ValueError`` on unknown keys / malformed numbers."""
    targets: Dict[str, SLOTarget] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        tenant, sep, body = entry.partition(":")
        tenant = tenant.strip()
        if not sep or not tenant:
            raise ValueError(
                f"bad SLO entry {entry!r}: expected 'tenant:key=val[,...]'")
        kv: Dict[str, float] = {}
        for tok in body.split(","):
            tok = tok.strip()
            if not tok:
                continue
            k, sep2, v = tok.partition("=")
            k = k.strip()
            if not sep2 or k not in ("p99_ms", "avail"):
                raise ValueError(
                    f"bad SLO entry {entry!r}: unknown key {k!r} "
                    "(expected p99_ms= and/or avail=)")
            try:
                kv[k] = float(v)
            except ValueError:
                raise ValueError(
                    f"bad SLO entry {entry!r}: {k}={v!r} is not a number")
        if not kv:
            raise ValueError(f"bad SLO entry {entry!r}: no objectives")
        if "avail" in kv and not (0.0 < kv["avail"] <= 100.0):
            raise ValueError(
                f"bad SLO entry {entry!r}: avail must be in (0, 100]")
        if "p99_ms" in kv and kv["p99_ms"] <= 0:
            raise ValueError(
                f"bad SLO entry {entry!r}: p99_ms must be > 0")
        targets[tenant] = SLOTarget(kv.get("p99_ms"), kv.get("avail"))
    return targets


class BurnRateEvaluator:
    """Per-tenant burn-rate state machine over a bounded event ring.

    ``record()`` is the serving hot-path hook (one lock + append);
    ``evaluate()`` recomputes both windows' burn rates, publishes the
    gauges, and advances the breach/recovery state machine.  The server
    runs ``evaluate`` on a small daemon thread; tests drive it directly
    with an injected clock.
    """

    def __init__(self, targets: Dict[str, SLOTarget],
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 threshold: float = 10.0,
                 hysteresis: float = 0.5,
                 clock=time.monotonic):
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                "SLO windows must satisfy 0 < fast <= slow "
                f"(got fast={fast_window_s}, slow={slow_window_s})")
        self.targets = dict(targets)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.threshold = float(threshold)
        self.hysteresis = float(hysteresis)
        self._clock = clock
        self._mu = threading.Lock()
        #: tenant -> deque[(t, bad)] trailing events  # guarded-by: _mu
        self._events: Dict[str, collections.deque] = {}
        self._breached: Dict[str, bool] = {}  # guarded-by: _mu
        self._last_burn: Dict[str, Tuple[float, float]] = {}  # guarded-by: _mu
        #: evicted tenants with an EXPLICIT spec entry: the declared-
        #: tenant loop must not re-mint their retired gauge series; new
        #: traffic (a re-admission) resumes reporting  # guarded-by: _mu
        self._forgotten: set = set()

    def _target(self, tenant: str) -> Optional[SLOTarget]:
        return self.targets.get(str(tenant), self.targets.get("*"))

    @staticmethod
    def _fold_tenant_gauges(tenant: str) -> None:
        """Drop every SLO gauge series of a tenant that stopped being
        tracked — the single place a new per-tenant SLO series must be
        added so eviction and idle-drop can't diverge."""
        for window in ("fast", "slow"):
            _monitor.SLO_BURN_GAUGE.fold(
                {"tenant": tenant, "window": window}, None)
        _monitor.SLO_BREACHED_GAUGE.fold({"tenant": tenant}, None)

    # -- hot path ------------------------------------------------------------
    def record(self, tenant: str, ok: bool, latency_ms: float = 0.0,
               now: Optional[float] = None) -> None:
        """One served-request outcome.  Tenants with no target (and no
        ``*`` default) are not tracked — recording them is free."""
        target = self._target(tenant)
        if target is None:
            return
        now = self._clock() if now is None else now
        bad = target.is_bad(ok, latency_ms)
        with self._mu:
            self._forgotten.discard(str(tenant))
            ring = self._events.get(str(tenant))
            if ring is None:
                ring = self._events[str(tenant)] = collections.deque(
                    maxlen=MAX_EVENTS_PER_TENANT)
            ring.append((now, 1 if bad else 0))

    def forget(self, tenant: str) -> None:
        """Stop tracking an evicted tenant.  The eviction path retires
        the tenant's registry series (``monitor.retire_tenant_series``);
        without this, the next ``evaluate()`` tick would re-mint the
        just-dropped SLO gauge series and the event/breach maps would
        grow without bound under tenant churn."""
        with self._mu:
            self._events.pop(str(tenant), None)
            self._breached.pop(str(tenant), None)
            self._last_burn.pop(str(tenant), None)
            if str(tenant) in self.targets:
                self._forgotten.add(str(tenant))
            # fold the gauge series HERE, under the same lock the
            # evaluator publishes under: an evaluate() tick that raced
            # the eviction (computed its publish set before retire_
            # tenant_series dropped the series) re-mints them — this
            # fold, serialized after that publish, takes them down again
            self._fold_tenant_gauges(str(tenant))

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Recompute burn rates for every tracked tenant, publish the
        gauges, fire breach/recovery transitions; returns the per-tenant
        state (what ``/statusz`` and the smoke read)."""
        now = self._clock() if now is None else now
        fast_cut = now - self.fast_window_s
        slow_cut = now - self.slow_window_s
        out: Dict[str, dict] = {}
        transitions: List[Tuple[str, str, float, float]] = []
        dropped: List[str] = []
        # one pass under the lock: prune, count both windows in a single
        # reversed scan (no ring snapshots — a near-full 100k ring would
        # otherwise stall the completion hot path's record() every tick),
        # and decide+commit transitions against the LIVE _breached state
        # (two concurrent evaluate() calls must fire ONE breach event)
        with self._mu:
            burns: Dict[str, Tuple[float, float]] = {}
            for tenant in list(self._events):
                ring = self._events[tenant]
                while ring and now - ring[0][0] > self.slow_window_s:
                    ring.popleft()
                if not ring and tenant not in self.targets \
                        and not self._breached.get(tenant, False):
                    # wildcard-matched tenant fully idle past the slow
                    # window: stop tracking it and drop its gauge series
                    # (bounds the evaluator AND the registry under tenant
                    # churn; a breached tenant first recovers — the
                    # recover instant must fire — then drops next tick)
                    del self._events[tenant]
                    self._breached.pop(tenant, None)
                    self._last_burn.pop(tenant, None)
                    dropped.append(tenant)
                    continue
                target = self._target(tenant)
                if target is None:
                    continue
                ft = fb = st = sb = 0
                for t, b in reversed(ring):
                    if t <= slow_cut:
                        break       # ring is time-ordered: done
                    st += 1
                    sb += b
                    if t > fast_cut:
                        ft += 1
                        fb += b
                burns[tenant] = ((fb / ft) / target.budget if ft else 0.0,
                                 (sb / st) / target.budget if st else 0.0)
            # declared tenants with no traffic yet still report (burn 0)
            # — except evicted ones, whose retired series must stay down
            for t in self.targets:
                if t != "*" and t not in burns and t not in self._forgotten:
                    burns[t] = (0.0, 0.0)
            for tenant, (fast, slow) in burns.items():
                breached = self._breached.get(tenant, False)
                if not breached and fast >= self.threshold \
                        and slow >= self.threshold:
                    breached = True
                    transitions.append((tenant, "breach", fast, slow))
                elif breached and fast <= self.threshold * self.hysteresis:
                    breached = False
                    transitions.append((tenant, "recover", fast, slow))
                self._breached[tenant] = breached
                self._last_burn[tenant] = (fast, slow)
                out[tenant] = {"burn_fast": fast, "burn_slow": slow,
                               "breached": breached,
                               "target": self._target(tenant).as_dict()}
            # publish while STILL holding _mu: forget() folds the
            # tenant's gauge series under this same lock, so an evict
            # racing this tick either lands before the publish (tenant
            # already absent from out) or after it (its fold takes the
            # just-published series down) — never a resurrected series
            for tenant in dropped:
                self._fold_tenant_gauges(tenant)
            for tenant, state in out.items():
                _monitor.SLO_BURN_GAUGE.set(round(state["burn_fast"], 4),
                                            tenant=tenant, window="fast")
                _monitor.SLO_BURN_GAUGE.set(round(state["burn_slow"], 4),
                                            tenant=tenant, window="slow")
                _monitor.SLO_BREACHED_GAUGE.set(
                    1 if state["breached"] else 0, tenant=tenant)
            for tenant, kind, fast, slow in transitions:
                if kind == "breach":
                    _monitor.SLO_BREACH_CTR.inc(1, tenant=tenant)
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        f"slo.{kind}", "slo",
                        {"tenant": tenant, "burn_fast": round(fast, 3),
                         "burn_slow": round(slow, 3),
                         "threshold": self.threshold})
        return out

    def in_breach(self, tenant: str) -> bool:
        with self._mu:
            return self._breached.get(str(tenant), False)

    def state(self) -> Dict[str, dict]:
        """Last evaluated view for ``/statusz`` (no recompute: the
        evaluator thread owns the cadence)."""
        with self._mu:
            return {t: {"burn_fast": fs[0], "burn_slow": fs[1],
                        "breached": self._breached.get(t, False),
                        "target": (self._target(t).as_dict()
                                   if self._target(t) else None)}
                    for t, fs in self._last_burn.items()}

    @classmethod
    def from_flags(cls) -> Optional["BurnRateEvaluator"]:
        """Build from ``FLAGS_serving_slo*``; None when no objectives
        are declared (the serving SLO plane is then fully off)."""
        from ..flags import get_flags
        fl = get_flags(["FLAGS_serving_slo",
                        "FLAGS_serving_slo_fast_window_s",
                        "FLAGS_serving_slo_slow_window_s",
                        "FLAGS_serving_slo_burn_threshold"])
        targets = parse_slo(str(fl["FLAGS_serving_slo"]))
        if not targets:
            return None
        return cls(targets,
                   fast_window_s=float(
                       fl["FLAGS_serving_slo_fast_window_s"]),
                   slow_window_s=float(
                       fl["FLAGS_serving_slo_slow_window_s"]),
                   threshold=float(
                       fl["FLAGS_serving_slo_burn_threshold"]))
