"""Multi-tenant inference server: tenant plane + graceful drain.

Admission is per tenant: every tenant gets a request counter, a
queue-depth gauge, and a latency histogram in ``paddle_tpu.monitor``
(series retire through ``monitor.retire_tenant_series`` on eviction — a
revolving tenant population cannot grow the registry), plus an outstanding
quota (``FLAGS_serving_tenant_quota`` or per-tenant overrides) enforced at
submit.

SIGTERM handling follows the PreemptionGuard pattern: the handler only
sets an Event (taking a metric/tracer lock while interrupting the main
thread's own critical section would self-deadlock at the exact moment the
drain must run); the serve loop then stops admitting (new submits reject
with reason="draining"), finishes every in-flight request, exports
telemetry, and returns exit code 0.
"""

from __future__ import annotations

import signal
import threading
import time
import warnings
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

from .. import monitor as _monitor
from .bucketing import BucketPlan, parse_buckets
from .scheduler import (ContinuousBatcher, DecodeScheduler, Request,
                        ServingFuture)


class TenantPlane:
    """Per-tenant admission + telemetry bookkeeping."""

    def __init__(self, default_quota: int = 0, on_evict=None):
        self._mu = threading.Lock()
        self._on_evict = on_evict    # eviction hook (e.g. slo.forget)
        self._outstanding: Dict[str, int] = {}  # guarded-by: _mu
        self._quotas: Dict[str, int] = {}  # guarded-by: _mu
        self._evicted: set = set()  # guarded-by: _mu
        # incarnation counter, bumped on evict: requests carry the
        # generation they were admitted under, so a straggler from a
        # PRE-eviction incarnation can neither decrement the re-admitted
        # tenant's quota nor re-mint the folded series
        self._gen: Dict[str, int] = {}  # guarded-by: _mu
        self._default_quota = int(default_quota)

    def generation(self, tenant: str) -> int:
        with self._mu:
            return self._gen.get(str(tenant), 0)

    def is_current(self, tenant: str, gen: Optional[int]) -> bool:
        """True when the request's admission incarnation is still live:
        tenant not evicted and (when the request carries one) its
        admission generation matches the current incarnation."""
        tenant = str(tenant)
        with self._mu:
            if tenant in self._evicted:
                return False
            return gen is None or gen == self._gen.get(tenant, 0)

    def set_quota(self, tenant: str, quota: int) -> None:
        with self._mu:
            self._quotas[str(tenant)] = int(quota)

    def try_admit(self, tenant: str) -> bool:
        """Reserve one outstanding unit; False when over quota (the
        caller counts the rejection)."""
        tenant = str(tenant)
        with self._mu:
            quota = self._quotas.get(tenant, self._default_quota)
            cur = self._outstanding.get(tenant, 0)
            if quota > 0 and cur >= quota:
                return False
            self._outstanding[tenant] = cur + 1
            depth = cur + 1
            # a fresh submit is a new incarnation: it may mint fresh
            # series again (and retire again on its own eviction)
            self._evicted.discard(tenant)
        _monitor.SERVING_REQ_CTR.inc(1, tenant=tenant)
        _monitor.SERVING_QUEUE_GAUGE.set(depth, tenant=tenant)
        return True

    def _account(self, tenant: str, gen: Optional[int]) -> tuple:
        """(label to account under, depth or None): requests of an
        EVICTED tenant — or an earlier incarnation of a re-admitted one
        (admission generation older than the current) — completing after
        the fold must land in the "retired" series, not resurrect the
        just-retired per-tenant ones or shrink the new incarnation's
        outstanding count."""
        with self._mu:
            stale = gen is not None and gen != self._gen.get(tenant, 0)
            if tenant in self._evicted or stale:
                return "retired", None
            depth = max(0, self._outstanding.get(tenant, 1) - 1)
            self._outstanding[tenant] = depth
            return tenant, depth

    def complete(self, tenant: str, latency_ms: float,
                 gen: Optional[int] = None) -> None:
        label, depth = self._account(str(tenant), gen)
        _monitor.SERVING_DONE_CTR.inc(1, tenant=label)
        _monitor.SERVING_LAT_HIST.observe(latency_ms, tenant=label)
        if depth is not None:
            _monitor.SERVING_QUEUE_GAUGE.set(depth, tenant=label)

    def fail(self, tenant: str, gen: Optional[int] = None) -> None:
        label, depth = self._account(str(tenant), gen)
        _monitor.SERVING_FAIL_CTR.inc(1, tenant=label)
        if depth is not None:
            _monitor.SERVING_QUEUE_GAUGE.set(depth, tenant=label)

    def reject(self, tenant: str, reason: str) -> None:
        tenant = str(tenant)
        with self._mu:
            if tenant in self._evicted:
                tenant = "retired"
        _monitor.SERVING_REJECT_CTR.inc(1, tenant=tenant, reason=reason)

    def snapshot(self) -> Dict[str, int]:
        """Per-tenant outstanding (queued + in-flight) counts — the
        ``/statusz`` queue-depth view."""
        with self._mu:
            return {t: n for t, n in self._outstanding.items()
                    if t not in self._evicted}

    def evict(self, tenant: str) -> None:
        """Drop the tenant and retire its registry series (PR-2 fold
        semantics: counters fold into tenant="retired", totals exact).
        In-flight requests of the tenant finish normally; their counts
        accrue to the "retired" series."""
        tenant = str(tenant)
        with self._mu:
            self._outstanding.pop(tenant, None)
            self._quotas.pop(tenant, None)
            self._evicted.add(tenant)
            self._gen[tenant] = self._gen.get(tenant, 0) + 1
        _monitor.retire_tenant_series(tenant)
        if self._on_evict is not None:
            self._on_evict(tenant)

    def outstanding(self, tenant: str) -> int:
        with self._mu:
            return self._outstanding.get(str(tenant), 0)


class _ServerBase:
    """Shared admission / drain / signal plumbing for both server modes."""

    def __init__(self, tenant_quota: Optional[int] = None,
                 max_retries: Optional[int] = None):
        from ..flags import get_flags
        from .slo import BurnRateEvaluator
        fl = get_flags(["FLAGS_serving_tenant_quota",
                        "FLAGS_serving_max_retries",
                        "FLAGS_serving_slo_shed",
                        "FLAGS_serving_slo_eval_interval_s"])
        quota = fl["FLAGS_serving_tenant_quota"] \
            if tenant_quota is None else tenant_quota
        self.tenants = TenantPlane(int(quota), on_evict=self._forget_slo)
        self._max_retries = int(fl["FLAGS_serving_max_retries"]
                                if max_retries is None else max_retries)
        self._draining = threading.Event()
        self._started = False
        self._old_handlers: Dict[int, Any] = {}
        self._sched = None       # set by the subclass
        #: per-tenant burn-rate state machine; None = SLO plane off
        self.slo = BurnRateEvaluator.from_flags()
        self._slo_shed = bool(fl["FLAGS_serving_slo_shed"])
        self._slo_interval = float(
            fl["FLAGS_serving_slo_eval_interval_s"])
        self._slo_stop = threading.Event()
        self._slo_thread: Optional[threading.Thread] = None
        self._slo_eval_warned = False
        self._http = None        # MetricsHTTPServer (enable_http)

    def _forget_slo(self, tenant: str) -> None:
        """Tenant-eviction hook: the evaluator must stop tracking the
        tenant or its next tick re-mints the SLO gauge series that
        ``retire_tenant_series`` just dropped."""
        if self.slo is not None:
            self.slo.forget(tenant)

    def _slo_eval_safe(self) -> None:
        """One evaluator tick.  The loop must outlive evaluator bugs,
        but not silently — a dead SLO plane showing breach-free gauges
        during an outage is worse than a crash, so the first failure
        warns with the error."""
        try:
            self.slo.evaluate()
        except Exception as e:
            if not self._slo_eval_warned:
                self._slo_eval_warned = True
                warnings.warn(
                    "serving SLO evaluator failed — burn/breach gauges "
                    f"are stale until it recovers: {e!r}")

    # -- admission -----------------------------------------------------------
    def _admit(self, tenant: str) -> Optional[str]:
        """None = admitted (one outstanding unit reserved); otherwise
        the rejection reason (already counted per tenant)."""
        if self._draining.is_set():
            self.tenants.reject(tenant, "draining")
            return "draining"
        if (self._slo_shed and self.slo is not None
                and self.slo.in_breach(tenant)):
            # shed-on-burn: while the tenant's SLO is in breach, new
            # work would only deepen the burn — refuse it at the door
            self.tenants.reject(tenant, "slo_shed")
            return "slo_shed"
        if not self.tenants.try_admit(tenant):
            self.tenants.reject(tenant, "quota")
            return "quota"
        return None

    def _on_complete(self, req: Request, result, latency_ms: float):
        req.future._resolve(result)
        self.tenants.complete(req.tenant, latency_ms, gen=req.admit_gen)
        # stale-generation guard mirrors TenantPlane._account: an
        # in-flight request resolving AFTER its tenant's eviction must
        # not un-forget the tenant and resurrect its retired SLO series
        if self.slo is not None \
                and self.tenants.is_current(req.tenant, req.admit_gen):
            self.slo.record(req.tenant, ok=True, latency_ms=latency_ms)

    def _on_fail(self, req: Request, err: BaseException):
        req.future._fail(err)
        self.tenants.fail(req.tenant, gen=req.admit_gen)
        if self.slo is not None \
                and self.tenants.is_current(req.tenant, req.admit_gen):
            self.slo.record(req.tenant, ok=False)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if not self._started:
            self._sched.start()
            self._started = True
            # OOM forensics census: a RESOURCE_EXHAUSTED dump includes
            # this server's memory section (bucket widths / KV page
            # occupancy) — weak registration, the dump never keeps a
            # stopped server alive
            from .. import hbm as _hbm
            _hbm.register_census(self.statusz)
        if self.slo is not None and self._slo_thread is None:
            self._slo_stop.clear()
            self._slo_thread = threading.Thread(
                target=self._slo_loop, name="serving-slo", daemon=True)
            self._slo_thread.start()
        return self

    def _slo_loop(self) -> None:
        while not self._slo_stop.wait(self._slo_interval):
            self._slo_eval_safe()

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Stop admitting and block until every in-flight request has
        resolved.  True when nothing was dropped."""
        self._draining.set()
        return self._sched.drain(timeout_s)

    def stop(self) -> None:
        self._draining.set()
        self._sched.stop()
        self._slo_stop.set()
        if self._slo_thread is not None:
            self._slo_thread.join(timeout=2.0)
            self._slo_thread = None      # start() can relaunch it
        if self.slo is not None:
            self._slo_eval_safe()          # final state for the export
        if self._http is not None:
            self._http.stop()
            self._http = None

    def queue_depth(self) -> int:
        return self._sched.queue_depth()

    # -- live scrape surface -------------------------------------------------
    def _health(self):
        draining = self._draining.is_set()
        return (not draining, "draining" if draining else "ok")

    def statusz(self) -> Dict[str, Any]:
        """Operational snapshot for ``/statusz`` (subclasses extend)."""
        return {"draining": self._draining.is_set(),
                "queue_depth": self.queue_depth(),
                "tenants": self.tenants.snapshot(),
                "slo": self.slo.state() if self.slo is not None else None}

    def enable_http(self, port: Optional[int] = None,
                    host: Optional[str] = None):
        """Start the /metrics /healthz /statusz endpoint for this
        server (idempotent).  ``port=None`` reads FLAGS_metrics_port —
        whose 0 default means DISABLED, so the call returns None rather
        than opening an unconfigured fleet-facing socket.  An explicit
        ``port=0`` argument binds an ephemeral port (read ``.port``).
        ``host=None`` reads FLAGS_metrics_host (default 0.0.0.0: the
        endpoint is fleet-facing — scrapers and balancers are
        off-box)."""
        if self._http is not None:
            return self._http
        from ..flags import get_flags
        if port is None:
            port = int(get_flags("FLAGS_metrics_port")
                       ["FLAGS_metrics_port"])
            if port <= 0:
                return None
        if host is None:
            host = str(get_flags("FLAGS_metrics_host")
                       ["FLAGS_metrics_host"])
        from .httpd import MetricsHTTPServer
        self._http = MetricsHTTPServer(
            port=int(port), host=host, health_fn=self._health,
            status_fn=self.statusz).start()
        return self._http

    # -- SIGTERM graceful drain (PreemptionGuard pattern) --------------------
    def install_signal_handlers(
            self, signals: Sequence[int] = (signal.SIGTERM,
                                            signal.SIGINT)) -> None:
        for s in signals:
            self._old_handlers[s] = signal.signal(s, self._handler)

    def _handler(self, signum, frame):
        # lock-free on purpose: only an Event.set — see module docstring
        self._draining.set()

    def serve_until_terminated(self, poll_s: float = 0.05,
                               drain_timeout_s: float = 60.0) -> int:
        """Block until SIGTERM/SIGINT, then drain and return the exit
        code (0 = zero dropped in-flight requests).  Exports telemetry
        when ``FLAGS_telemetry_export_path`` is set (at-exit hook);
        exposes the live scrape endpoint when ``FLAGS_metrics_port`` is
        set (``/healthz`` flips to 503 the moment draining starts, so a
        balancer can eject the replica before the drain finishes)."""
        self.install_signal_handlers()
        from ..flags import get_flags
        if self._http is None and int(
                get_flags("FLAGS_metrics_port")["FLAGS_metrics_port"]) > 0:
            self.enable_http()
        try:
            while not self._draining.is_set():
                time.sleep(poll_s)
            ok = self.drain(drain_timeout_s)
        finally:
            for s, h in self._old_handlers.items():
                signal.signal(s, h)
            self._old_handlers.clear()
            self.stop()
        return 0 if ok else 1


class InferenceServer(_ServerBase):
    """Bucketized continuous-batching server for request/response models.

    ``program_factory(seq_len) -> (program, feed_names, fetch_names)``
    materializes the model at one bucket length (Fluid programs bake the
    sequence length into op attrs, so each bucket is its own program —
    all sharing one scope of parameters).  Each bucket compiles ONCE
    (fixed width x bucket feed shapes through ``compiler.optimize`` with
    the verifier/cost/memory stamps riding along) and persists via
    ``FLAGS_xla_compile_cache_dir``, so a server restart is warm and the
    compile count equals the bucket count — never the number of distinct
    request shapes.
    """

    def __init__(self, program_factory: Callable[[int], tuple], scope,
                 buckets=None, max_batch: Optional[int] = None,
                 max_seq: Optional[int] = None, executor=None,
                 tenant_quota: Optional[int] = None,
                 batch_wait_ms: Optional[float] = None,
                 max_retries: Optional[int] = None):
        super().__init__(tenant_quota, max_retries)
        from ..flags import get_flags
        from ..framework.executor import Executor
        fl = get_flags(["FLAGS_serving_shape_buckets",
                        "FLAGS_serving_max_batch",
                        "FLAGS_serving_batch_wait_ms",
                        "FLAGS_memory_budget_mb"])
        if buckets is None:
            buckets = parse_buckets(fl["FLAGS_serving_shape_buckets"],
                                    max_len=int(max_seq or 512))
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.scope = scope
        self.executor = executor or Executor()
        self.plan = BucketPlan(
            self.buckets, program_factory,
            int(max_batch or fl["FLAGS_serving_max_batch"]),
            memory_budget_mb=int(fl["FLAGS_memory_budget_mb"]))
        self._sched = ContinuousBatcher(
            self.executor, scope, self.plan,
            on_complete=self._on_complete, on_fail=self._on_fail,
            max_retries=self._max_retries,
            batch_wait_ms=float(fl["FLAGS_serving_batch_wait_ms"]
                                if batch_wait_ms is None else
                                batch_wait_ms))

    def warmup(self, buckets=None) -> int:
        """Compile each bucket once with a dummy full-width batch —
        after this the steady-state compile counter is flat and a
        restart hits the persistent XLA disk cache.  Returns the number
        of buckets warmed."""
        n = 0
        for b in (buckets or self.buckets):
            compiled, feed_names, fetch_names, width = self.plan.plan(b)
            feed = {}
            program = compiled.program
            block = program.global_block()
            for name in feed_names:
                var = block.var(name)
                shape = [width] + [b if d == -1 or d is None else int(d)
                                   for d in (var.shape or ())[1:]]
                # the DECLARED dtype: the compiled-block key includes the
                # feed signature, so a warmup in the wrong dtype would
                # compile a bucket no real request ever hits
                dt = np.dtype(str(var.dtype or "float32"))
                feed[name] = np.zeros(shape, dt)
            self.executor.run(compiled, feed=feed,
                              fetch_list=list(fetch_names),
                              scope=self.scope, return_numpy=True)
            n += 1
        return n

    def submit(self, tenant: str, feeds: Dict[str, Any],
               seq_len: Optional[int] = None) -> ServingFuture:
        """Queue one request (per-example feeds, NO batch dim) and return
        its future.  Rejected requests get a future already failed with
        :class:`AdmissionError` — callers never block on admission.
        ``seq_len`` overrides the TRIM length of the fetches; the bucket
        is always chosen to fit every feed (a caller-understated length
        must not smuggle an oversize array past padding)."""
        t0 = time.perf_counter()
        feeds = {k: np.asarray(v) for k, v in feeds.items()}
        longest = max((a.shape[0] for a in feeds.values() if a.ndim),
                      default=0)
        n = int(seq_len) if seq_len is not None else longest
        bucket = self.plan.bucket_for(max(n, longest))
        if bucket is None:
            self.tenants.reject(tenant, "too_long")
            f = ServingFuture()
            f._fail(AdmissionError(
                f"request length {max(n, longest)} exceeds the largest "
                f"bucket {self.buckets[-1]}"))
            return f
        reason = self._admit(tenant)
        if reason is not None:
            f = ServingFuture()
            f._fail(AdmissionError(
                f"tenant {tenant!r} rejected ({reason})"))
            return f
        req = Request(tenant, feeds=feeds, seq_len=n, bucket=bucket)
        # the admit phase starts at submit ENTRY (bucket choice + quota
        # accounting belong to it), so the phase chain partitions the
        # whole measured e2e latency
        req.t_submit = t0
        req.tm["submit"] = t0
        req.admit_gen = self.tenants.generation(tenant)
        if not self._sched.enqueue(req):
            # enqueue raced stop(): nothing will ever service the queue
            self._on_fail(req, AdmissionError("server stopped"))
        return req.future

    def compile_stats(self) -> Dict[str, int]:
        st = self.executor.dispatch_stats()
        return {"traces": int(st["traces"]),
                "compiled_blocks": int(st.get("compiled_blocks", 0)),
                "buckets": len(self.buckets)}

    def shrink_widths(self) -> Dict[int, int]:
        """Degradation-ladder actuator (fleet autoscaler ``control`` op):
        halve every built bucket's admitted batch width.  Delegates to
        the :class:`~paddle_tpu.serving.bucketing.BucketPlan`; the
        scheduler picks the new width up on its next dispatch."""
        return self.plan.shrink_widths()

    def statusz(self) -> Dict[str, Any]:
        out = super().statusz()
        out["buckets"] = {str(b): self.plan.width_of(b)
                         for b in self.buckets}
        out["compile"] = self.compile_stats()
        # memory section: the budget in force + each BUILT bucket's
        # admitted width and static HBM peak at that width (cold
        # buckets report null — statusz never triggers a build)
        from ..flags import get_flags
        out["memory"] = {
            "budget_mb": int(get_flags("FLAGS_memory_budget_mb")
                             ["FLAGS_memory_budget_mb"]),
            "per_bucket": {
                str(b): {"width": self.plan.width_of(b),
                         "static_peak_bytes": self.plan.static_peak_of(b)}
                for b in self.buckets},
        }
        occ = _monitor.REGISTRY.get("paddle_tpu_serving_batch_occupancy")
        if occ is not None:
            tot_sum = tot_n = 0.0
            for labels, cell in occ.series():
                if labels.get("mode") != "batch":
                    continue    # a coexisting decode loop's iterations
                _counts, s, c = cell.snapshot()
                tot_sum += s
                tot_n += c
            if tot_n:
                out["mean_occupancy"] = round(tot_sum / tot_n, 3)
        return out


class DecodeServer(_ServerBase):
    """Continuous-batching token-generation server (``gpt_causal``).

    Wraps a :class:`~paddle_tpu.serving.kv_cache.DecodeEngine`: requests
    carry a prompt + ``max_new_tokens``; the decode loop admits them into
    KV slots, prefills and generates through ONE compiled step, and frees
    the paged cache on completion — slot reuse across requests with the
    compile counter flat after warmup."""

    def __init__(self, engine, tenant_quota: Optional[int] = None,
                 max_retries: Optional[int] = None):
        super().__init__(tenant_quota, max_retries)
        self.engine = engine
        self._sched = DecodeScheduler(
            engine, on_complete=self._on_complete, on_fail=self._on_fail,
            max_retries=self._max_retries)

    def submit(self, tenant: str, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> ServingFuture:
        t0 = time.perf_counter()
        prompt = np.asarray(prompt).ravel()
        if prompt.size == 0:
            self.tenants.reject(tenant, "too_long")
            f = ServingFuture()
            f._fail(AdmissionError("empty prompt"))
            return f
        if prompt.size + int(max_new_tokens) > self.engine.max_seq:
            self.tenants.reject(tenant, "too_long")
            f = ServingFuture()
            f._fail(AdmissionError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds the engine context window "
                f"{self.engine.max_seq}"))
            return f
        reason = self._admit(tenant)
        if reason is not None:
            f = ServingFuture()
            f._fail(AdmissionError(
                f"tenant {tenant!r} rejected ({reason})"))
            return f
        req = Request(tenant, prompt=prompt,
                      max_new_tokens=int(max_new_tokens), eos_id=eos_id)
        req.t_submit = t0
        req.tm["submit"] = t0
        req.admit_gen = self.tenants.generation(tenant)
        if not self._sched.enqueue(req):
            self._on_fail(req, AdmissionError("server stopped"))
        return req.future

    def compile_stats(self) -> Dict[str, int]:
        return {"traces": int(self.engine.trace_count),
                "kv_pages_in_use": self.engine.cache.pages_in_use()}

    def statusz(self) -> Dict[str, Any]:
        out = super().statusz()
        free = sum(1 for s in self._sched._slots if s is None)
        out["slots"] = {"total": self.engine.max_slots, "free": free}
        out["kv_pages_in_use"] = self.engine.cache.pages_in_use()
        out["tokens_per_s"] = float(_monitor.SERVING_TPS_GAUGE.value()) \
            if _monitor.REGISTRY.get(
                "paddle_tpu_serving_tokens_per_s").series() else 0.0
        # memory section: budget + KV pool census with per-tenant page
        # occupancy and internal fragmentation (retire-on-eviction fold
        # keeps the backing gauges bounded across tenant churn)
        from ..flags import get_flags
        cache = self.engine.cache
        out["memory"] = {
            "budget_mb": int(get_flags("FLAGS_memory_budget_mb")
                             ["FLAGS_memory_budget_mb"]),
            "kv": {"page_len": int(self.engine.page_len),
                   "pages_total": int(cache.n_pages),
                   "pages_in_use": int(cache.pages_in_use()),
                   "pool_bytes": int(cache.pool_bytes()),
                   "per_tenant": self._sched.kv_census()},
        }
        return out


class AdmissionError(RuntimeError):
    """A request refused at admission (quota / draining / too long)."""
