"""Bucketized shape cache: pad arbitrary request shapes onto a small set
of compile buckets so serving compile cost is bounded by the bucket count,
not the number of distinct request shapes (the TVM-style AOT shape-bucket
design — PAPERS.md arxiv 1802.04799).

A bucket is a sequence length; every feed of a request is padded along its
leading (per-example sequence) axis up to the bucket, and the batch is
padded to a FIXED per-bucket width — so each bucket lowers to exactly one
XLA executable, persisted across restarts via
``FLAGS_xla_compile_cache_dir``.  Fluid programs bake the sequence length
into op attrs (position-table slices, causal-mask ranges), so the server
materializes one program per bucket through a ``program_factory`` and runs
each through ``compiler.optimize`` — the verifier / cost / memory stamps
ride along on every bucket program.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from .. import monitor as _monitor

BUCKET_WIDTH_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_serving_bucket_width",
    "admitted batch width per compile bucket (lowered below "
    "FLAGS_serving_max_batch when the static HBM plan at full width "
    "exceeds FLAGS_memory_budget_mb)", ("bucket",))
PAD_TOKENS_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_serving_padding_rows_total",
    "dummy batch rows dispatched to keep bucket shapes fixed (the "
    "occupancy complement: rows = batches*width - real requests)")


def parse_buckets(spec: str, max_len: int = 512) -> Tuple[int, ...]:
    """``FLAGS_serving_shape_buckets`` grammar: ``"16,32,64"`` explicit,
    ``"pow2:LO:HI"`` powers of two from LO to HI inclusive, ``""`` =
    powers of two from 8 up to ``max_len``."""
    spec = (spec or "").strip()
    if not spec:
        buckets, b = [], 8
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
        return tuple(sorted(set(buckets)))
    if spec.startswith("pow2:"):
        try:
            _, lo, hi = spec.split(":")
            lo, hi = int(lo), int(hi)
        except ValueError:
            raise ValueError(
                f"bad bucket spec {spec!r}: expected 'pow2:LO:HI'")
        if lo <= 0 or hi < lo:
            raise ValueError(f"bad bucket spec {spec!r}: need 0 < LO <= HI")
        buckets, b = [], lo
        while b < hi:
            buckets.append(b)
            b *= 2
        buckets.append(hi)
        return tuple(sorted(set(buckets)))
    try:
        buckets = tuple(sorted({int(tok) for tok in spec.split(",") if tok}))
    except ValueError:
        raise ValueError(
            f"bad bucket spec {spec!r}: expected comma-separated ints or "
            "'pow2:LO:HI'")
    if not buckets or any(b <= 0 for b in buckets):
        raise ValueError(f"bad bucket spec {spec!r}: buckets must be > 0")
    return buckets


def bucket_for(seq_len: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket that fits ``seq_len``; None when it exceeds the
    largest bucket (the request is rejected at admission, not truncated)."""
    for b in buckets:
        if seq_len <= b:
            return b
    return None


def pad_to_bucket(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad one per-example feed along its leading axis up to ``bucket``
    with zeros (0 is the [PAD] id convention throughout this repo).
    Scalars and feeds already at the bucket pass through."""
    a = np.asarray(arr)
    if a.ndim == 0 or a.shape[0] == bucket:
        return a
    if a.shape[0] > bucket:
        raise ValueError(
            f"feed of length {a.shape[0]} exceeds bucket {bucket}")
    pad = [(0, bucket - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)


class BucketPlan:
    """Per-bucket execution plan: the bucket program (built once through
    ``program_factory`` and wrapped in a CompiledProgram so dispatch goes
    through ``compiler.optimize`` — verifier/cost/memory stamps ride
    along) plus the admitted batch width.

    Width admission control (PR-7 static HBM plan): when
    ``FLAGS_memory_budget_mb`` is set, the width starts at
    ``FLAGS_serving_max_batch`` and halves until the bucket program's
    static peak fits the budget — an over-budget bucket serves narrower
    batches instead of OOMing the chip."""

    def __init__(self, buckets: Sequence[int],
                 program_factory: Callable[[int], tuple],
                 max_batch: int, memory_budget_mb: int = 0):
        self.buckets = tuple(sorted(buckets))
        self._factory = program_factory
        self._max_batch = max(1, int(max_batch))
        self._budget = int(memory_budget_mb)
        self._plans: Dict[int, tuple] = {}  # guarded-by: _mu
        self._mu = threading.Lock()

    def plan(self, bucket: int):
        """(compiled_program, feed_names, fetch_names, width) for one
        bucket — built on first use, memoized after."""
        with self._mu:
            entry = self._plans.get(bucket)
        if entry is not None:
            return entry
        from ..compiler import CompiledProgram
        program, feed_names, fetch_names = self._factory(bucket)
        feed_names = [getattr(f, "name", f) for f in feed_names]
        fetch_names = [getattr(f, "name", f) for f in fetch_names]
        width = self._admit_width(program, fetch_names)
        entry = (CompiledProgram(program), list(feed_names),
                 list(fetch_names), width)
        BUCKET_WIDTH_GAUGE.set(width, bucket=str(bucket))
        with self._mu:
            # first build wins — a concurrent builder's duplicate is
            # dropped so every caller dispatches the same CompiledProgram
            # (and hence the same compiled block)
            entry = self._plans.setdefault(bucket, entry)
        return entry

    def _admit_width(self, program, fetch_names) -> int:
        width = self._max_batch
        if self._budget <= 0:
            return width
        from ..analysis.memory import plan_memory
        budget_bytes = self._budget * (1 << 20)
        while width > 1:
            try:
                plan = plan_memory(program, tuple(fetch_names),
                                   batch_size=width)
            except Exception:
                return width        # planning must never block serving
            if plan.peak_bytes <= budget_bytes:
                return width
            width //= 2
        return width

    def shrink_widths(self) -> Dict[int, int]:
        """Halve the admitted width of every ALREADY-BUILT bucket (floor
        1) — the fleet autoscaler's degradation-ladder rung for a replica
        reporting OOM-risk headroom.  The scheduler re-reads the admitted
        width from the memoized entry on every dispatch, so the shrink
        takes effect on the next batch (one fresh XLA compile per shrunk
        bucket — an acceptable one-time cost against an imminent OOM).
        Cold buckets are untouched: they will admit at their planned
        width when first built.  Returns {bucket: new_width}."""
        out: Dict[int, int] = {}
        with self._mu:
            for bucket, entry in list(self._plans.items()):
                compiled, feeds, fetches, width = entry
                new = max(1, int(width) // 2)
                if new != width:
                    self._plans[bucket] = (compiled, feeds, fetches, new)
                out[bucket] = new
        for bucket, w in out.items():
            BUCKET_WIDTH_GAUGE.set(w, bucket=str(bucket))
        return out

    def width_of(self, bucket: int) -> Optional[int]:
        """Admitted width of an ALREADY-BUILT bucket plan; None for a
        cold bucket (statusz must never trigger a build/compile)."""
        with self._mu:
            entry = self._plans.get(int(bucket))
        return entry[3] if entry is not None else None

    def static_peak_of(self, bucket: int) -> Optional[int]:
        """Static HBM peak (bytes) of an ALREADY-BUILT bucket program at
        its admitted width — the /statusz memory section's per-bucket
        plan.  Fingerprint-cached (plan_memory), so a statusz scrape
        never re-plans; None for cold buckets or on planner failure."""
        with self._mu:
            entry = self._plans.get(int(bucket))
        if entry is None:
            return None
        compiled, _feeds, fetch_names, width = entry
        try:
            from ..analysis.memory import plan_memory
            return int(plan_memory(compiled.program, tuple(fetch_names),
                                   batch_size=width).peak_bytes)
        except Exception:
            return None

    def bucket_for(self, seq_len: int) -> Optional[int]:
        return bucket_for(seq_len, self.buckets)
