"""Serving fleet: replica endpoints + the :class:`FleetRouter` front door.

A *fleet* is N serving replicas (each an
:class:`~paddle_tpu.serving.server.InferenceServer` or
:class:`~paddle_tpu.serving.server.DecodeServer` behind a
:class:`ReplicaEndpoint`) fronted by one :class:`FleetRouter`.  The
router speaks the gang coordinator's length-prefixed frame protocol to
each replica, places every request on the least-loaded healthy replica,
and absorbs replica failure: a replica dying mid-batch re-routes the
in-flight idempotent request to a survivor instead of surfacing a
client-visible error.

Placement (``FLAGS_fleet_route_policy``):

* ``least_loaded`` (default) — the fresh, non-draining,
  breaker-closed replica with the smallest ``srv_q`` (queued requests
  from its heartbeat-digest load report); round-robin tie-break so
  equal replicas share warmup traffic.
* ``round_robin`` — strict rotation over the healthy set.

Freshness: a replica's load report ages out after
``FLAGS_fleet_digest_ttl_s`` seconds without contact (a reply or a
prober round-trip both refresh it).  A stale replica is held OUT of
placement — a dead replica's last digest can never keep attracting
traffic — but the prober keeps knocking, so a replica that was merely
slow rejoins the pool on its next successful probe.

Failure handling per forward attempt:

* connection refused / reset / torn frame → the replica is marked
  ``dead``, its circuit breaker opens, and the request re-routes
  (``reason="dead"``);
* an explicit ``draining`` refusal (SIGTERM'd replica running its
  guard-path drain) → marked ``draining``, re-route
  (``reason="drain"``) — the drain itself finishes the replica's
  in-flight work, so the fleet drops nothing;
* an open breaker skips the replica without touching the wire
  (``reason="circuit"``);
* any other refusal re-routes once as ``reason="error"``.

Re-routes ride the PR-3 retry engine: a deterministic jittered backoff
ladder, capped by the policy deadline, with every re-route counted in
``paddle_tpu_fleet_reroutes_total{reason}`` — the counter ledger a
chaos drill can assert exactly.

Quota consistency: the router runs its own fleet-wide
:class:`~paddle_tpu.serving.server.TenantPlane`, so a tenant's quota
bounds its outstanding requests across ALL replicas — N replicas do not
multiply a tenant's budget by N.  Admission happens once at the router;
replicas are given router traffic with their own per-replica quota
disabled (quota=0 ⇒ unlimited) or generously sized.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import monitor as _monitor
from .. import resilience as _resil
from ..distributed.coordinator import recv_frame, send_frame
from .server import AdmissionError, TenantPlane

__all__ = ["ReplicaEndpoint", "FleetRouter", "FleetError"]

#: FLEET_REPLICA_STATE gauge encoding (documented on the family)
_STATE_CODE = {"up": 0, "draining": 1, "dead": 2, "stale": 3}


class FleetError(RuntimeError):
    """The fleet could not complete a request: every placement candidate
    failed or the retry deadline elapsed."""


# ---------------------------------------------------------------------------
# replica side: a frame-protocol endpoint in front of one serving server
# ---------------------------------------------------------------------------

class ReplicaEndpoint:
    """TCP front for ONE serving server, speaking the coordinator's
    frame protocol (4-byte BE length + JSON).

    Ops: ``infer`` (InferenceServer.submit), ``decode``
    (DecodeServer.submit), ``status`` (load probe).  Every reply carries
    the replica's current load report (``srv_q``/``occ``/``slots``/
    ``tps`` where available) and its ``draining`` bit, so each response
    doubles as a freshness heartbeat for the router's placement table.
    """

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 replica_id: Optional[str] = None):
        self.server = server
        self.host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self.replica_id = replica_id or f"replica-{os.getpid()}"
        self._lsock: Optional[socket.socket] = None
        self._mu = threading.Lock()
        self._stopping = False                  # guarded-by: _mu
        self._conns: List[socket.socket] = []   # guarded-by: _mu
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaEndpoint":
        if self._lsock is not None:
            return self
        with self._mu:
            self._stopping = False
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self._requested_port))
        s.listen(128)
        self._lsock = s
        self.port = s.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="pt-replica-accept")
        t.start()
        self._threads.append(t)
        return self

    @property
    def address(self) -> str:
        if self.port is None:
            raise RuntimeError("replica endpoint not started")
        return f"{self.host}:{self.port}"

    def stop(self) -> None:
        with self._mu:
            self._stopping = True
            conns, self._conns = self._conns, []
        if self._lsock is not None:
            # close() alone does NOT wake a thread blocked in accept():
            # the in-flight syscall keeps the LISTEN socket alive in the
            # kernel, which keeps completing handshakes nobody serves —
            # a "stopped" replica that still looks connectable hangs
            # clients until timeout instead of refusing fast
            try:
                self._lsock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._lsock.close()
            except OSError:
                pass
            self._lsock = None
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- accept / serve ------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._lsock.accept()
            except (OSError, AttributeError):
                return
            with self._mu:
                if self._stopping:
                    conn.close()
                    return
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="pt-replica-conn")
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                req = recv_frame(conn)
                try:
                    resp = self._handle(req)
                except Exception as e:     # a bad request must not kill
                    resp = {"ok": False,   # the endpoint
                            "error": "internal",
                            "detail": repr(e)[:300]}
                resp.setdefault("replica", self.replica_id)
                resp.setdefault("load", self._load())
                resp.setdefault("draining", self._draining())
                send_frame(conn, resp)
        except (ConnectionError, OSError, ValueError):
            pass                           # client went away / bad frame
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._mu:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- load report ---------------------------------------------------------
    def _draining(self) -> bool:
        return bool(self.server._draining.is_set())

    def _load(self) -> Dict[str, float]:
        """The placement digest: queue depth from the server itself (the
        authoritative number), the occupancy/slot/throughput keys from
        the monitor digest when the scheduler is alive to report them."""
        load = {"srv_q": float(self.server.queue_depth())}
        try:
            digest = _monitor.metrics_digest()
        except Exception:
            digest = {}
        # hbm/hdrm ride along when the HBM accountant publishes them —
        # the autoscaler's OOM-risk headroom signal (degradation ladder)
        for k in ("occ", "slots", "tps", "hbm", "hdrm"):
            if k in digest:
                load[k] = float(digest[k])
        return load

    # -- ops -----------------------------------------------------------------
    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "status":
            return {"ok": True}
        if op == "infer":
            return self._op_infer(req)
        if op == "decode":
            return self._op_decode(req)
        if op == "control":
            return self._op_control(req)
        return {"ok": False, "error": "unknown_op", "detail": str(op)}

    def _op_control(self, req: dict) -> dict:
        """Autoscaler control plane.  ``shrink_width`` is the degradation
        ladder's first rung: halve this replica's admitted bucket widths
        to claw back HBM headroom.  A server without the actuator (e.g.
        DecodeServer — no BucketPlan) answers ``unsupported``, which
        escalates the controller's ladder straight to drain-and-respawn."""
        cmd = req.get("cmd")
        if cmd == "shrink_width":
            fn = getattr(self.server, "shrink_widths", None)
            if fn is None:
                return {"ok": False, "error": "unsupported",
                        "detail": f"{type(self.server).__name__} has no "
                                  "bucket plan to shrink"}
            try:
                widths = fn()
            except Exception as e:
                return {"ok": False, "error": "internal",
                        "detail": repr(e)[:300]}
            return {"ok": True,
                    "widths": {str(b): int(w) for b, w in widths.items()}}
        return {"ok": False, "error": "unknown_cmd", "detail": str(cmd)}

    @staticmethod
    def _admission_reply(e: AdmissionError) -> dict:
        # the draining refusal is a ROUTING signal (re-route, don't
        # fail); every other admission verdict is final for this replica
        msg = str(e)
        if "draining" in msg:
            return {"ok": False, "error": "draining", "detail": msg}
        return {"ok": False, "error": "admission", "detail": msg}

    def _op_infer(self, req: dict) -> dict:
        feeds = {}
        for name, spec in (req.get("feeds") or {}).items():
            feeds[name] = np.asarray(spec["data"],
                                     dtype=spec.get("dtype") or None)
        try:
            fut = self.server.submit(str(req.get("tenant", "default")),
                                     feeds, seq_len=req.get("seq_len"))
            result = fut.result(timeout=float(req.get("timeout_s", 30.0)))
        except AdmissionError as e:
            return self._admission_reply(e)
        outputs = [np.asarray(a).tolist() for a in (result or [])]
        return {"ok": True, "outputs": outputs}

    def _op_decode(self, req: dict) -> dict:
        try:
            fut = self.server.submit(
                str(req.get("tenant", "default")),
                list(req.get("prompt") or []),
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                eos_id=req.get("eos_id"))
            result = fut.result(timeout=float(req.get("timeout_s", 30.0)))
        except AdmissionError as e:
            return self._admission_reply(e)
        return {"ok": True, "tokens": np.asarray(result).tolist()}


# ---------------------------------------------------------------------------
# router side: placement + re-route
# ---------------------------------------------------------------------------

class FleetRouter:
    """Fleet front door: places each request on the best healthy replica
    and re-routes around failures (see module docstring for the policy
    and failure taxonomy)."""

    def __init__(self, replicas: Sequence[str],
                 policy: Optional[str] = None,
                 digest_ttl_s: Optional[float] = None,
                 tenant_quota: int = 0,
                 request_timeout_s: float = 30.0,
                 retry_policy: Optional[_resil.RetryPolicy] = None):
        from ..flags import get_flags
        fl = get_flags(["FLAGS_fleet_route_policy",
                        "FLAGS_fleet_digest_ttl_s"])
        self.policy = str(policy or fl["FLAGS_fleet_route_policy"])
        self.digest_ttl_s = float(digest_ttl_s if digest_ttl_s is not None
                                  else fl["FLAGS_fleet_digest_ttl_s"])
        self.request_timeout_s = float(request_timeout_s)
        #: fleet-wide quota plane — ONE admission decision per request,
        #: made here, so N replicas never multiply a tenant's budget
        self.tenants = TenantPlane(default_quota=int(tenant_quota))
        # generous default ladder: enough attempts to visit every
        # replica plus backoff headroom, bounded by a hard deadline so a
        # wedged fleet fails the client loudly instead of forever
        self._retry = retry_policy or _resil.RetryPolicy(
            max_attempts=max(4, 2 * len(replicas) + 2),
            base_delay_s=0.02, max_delay_s=0.25,
            deadline_s=self.request_timeout_s)
        self._mu = threading.Lock()
        self._reps: Dict[str, dict] = {}        # guarded-by: _mu
        for addr in replicas:
            self._reps[str(addr)] = {
                "state": "up", "load": {}, "last_seen": 0.0,
                "breaker": _resil.CircuitBreaker(name=f"fleet.{addr}"),
            }
            _monitor.FLEET_REPLICA_STATE.set(_STATE_CODE["up"],
                                             replica=str(addr))
        self._rr = 0                            # guarded-by: _mu
        self._stats = {"admitted": 0, "completed": 0,  # guarded-by: _mu
                       "failed": 0, "rejected": 0}
        #: autoscaler shed switch: while True, _admit rejects every new
        #: request with reason="slo_shed" (cheap backpressure while a
        #: spawn is in flight or the fleet is pinned at max)
        self._shedding = False                  # guarded-by: _mu
        # fleet-level SLO plane: the router records every request's e2e
        # outcome, so the autoscaler reads ONE burn-rate signal for the
        # whole fleet (per-replica evaluators see only their slice of
        # traffic and none of the routing/retry latency).  None when
        # FLAGS_serving_slo is empty — the controller then scales on
        # queue pressure alone.
        from .slo import BurnRateEvaluator
        self.slo = BurnRateEvaluator.from_flags()
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._prober is None or not self._prober.is_alive():
            self._stop.clear()
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True,
                                            name="pt-fleet-prober")
            self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=2.0)
            self._prober = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- replica table -------------------------------------------------------
    def _set_state_locked(self, addr: str,  # guarded-by-caller: _mu
                          state: str) -> None:
        rep = self._reps[addr]
        if rep["state"] != state:
            rep["state"] = state
            _monitor.FLEET_REPLICA_STATE.set(_STATE_CODE[state],
                                             replica=addr)

    def _note_reply(self, addr: str, resp: dict) -> None:
        """Any reply from a replica refreshes its freshness clock and
        load report — replies ARE the router's heartbeat plane."""
        with self._mu:
            rep = self._reps.get(addr)
            if rep is None:
                return
            rep["last_seen"] = time.monotonic()
            # ANY reply proves the transport works: close the breaker
            # (a half-open probe that got an answer succeeded, even a
            # "draining" refusal — state still holds the replica out)
            rep["breaker"].record_success()
            load = resp.get("load")
            if isinstance(load, dict):
                rep["load"] = load
            if resp.get("draining"):
                self._set_state_locked(addr, "draining")
            elif rep["state"] in ("dead", "stale", "draining"):
                self._set_state_locked(addr, "up")

    def _mark_dead(self, addr: str) -> None:
        with self._mu:
            rep = self._reps.get(addr)
            if rep is None:
                return
            self._set_state_locked(addr, "dead")
            rep["breaker"].record_giveup()

    def _mark_draining(self, addr: str) -> None:
        with self._mu:
            if addr in self._reps:
                self._set_state_locked(addr, "draining")

    def add_replica(self, addr: str) -> None:
        """Admit a freshly spawned replica into placement (autoscaler
        scale-up / death repair).  Idempotent; the new replica enters
        with no load report and proves freshness on its first probe or
        reply."""
        addr = str(addr)
        with self._mu:
            if addr in self._reps:
                return
            self._reps[addr] = {
                "state": "up", "load": {}, "last_seen": 0.0,
                "breaker": _resil.CircuitBreaker(name=f"fleet.{addr}"),
            }
            _monitor.FLEET_REPLICA_STATE.set(_STATE_CODE["up"],
                                             replica=addr)

    def remove_replica(self, addr: str) -> None:
        """Drop a retired/dead replica from the table (autoscaler
        retire path, AFTER its drain finished).  Folds the replica's
        state gauge series so the registry does not grow with fleet
        churn (PR-2 retirement semantics)."""
        addr = str(addr)
        with self._mu:
            rep = self._reps.pop(addr, None)
        if rep is not None:
            _monitor.FLEET_REPLICA_STATE.fold({"replica": addr}, None)

    def set_shedding(self, on: bool) -> None:
        """Engage/release fleet-wide admission shedding (the autoscaler's
        shed-vs-scale arbitration actuator)."""
        with self._mu:
            self._shedding = bool(on)

    def replica_view(self) -> Dict[str, dict]:
        """The autoscaler's per-replica signal view: placement state,
        last load report (srv_q + the digest keys incl. hbm/hdrm), and
        whether the load report is fresh under the digest TTL."""
        now = time.monotonic()
        with self._mu:
            return {a: {"state": r["state"],
                        "load": dict(r["load"]),
                        "fresh": bool(r["last_seen"] and
                                      now - r["last_seen"]
                                      <= self.digest_ttl_s)}
                    for a, r in self._reps.items()}

    def control(self, addr: str, cmd: str,
                timeout_s: float = 5.0) -> dict:
        """Send one control op (e.g. ``shrink_width``) directly to a
        replica — control traffic never routes through placement."""
        resp = self._call(addr, {"op": "control", "cmd": str(cmd)},
                          timeout_s)
        self._note_reply(addr, resp)
        return resp

    def snapshot(self) -> Dict[str, Any]:
        """Operational view: per-replica state/load/freshness plus the
        router's exact request ledger (admitted == completed + failed +
        in-flight; the chaos drill asserts this sums)."""
        now = time.monotonic()
        with self._mu:
            reps = {a: {"state": r["state"],
                        "load": dict(r["load"]),
                        "age_s": (round(now - r["last_seen"], 3)
                                  if r["last_seen"] else None),
                        "breaker": r["breaker"].state}
                    for a, r in self._reps.items()}
            return {"replicas": reps, "policy": self.policy,
                    "ttl_s": self.digest_ttl_s,
                    "shedding": self._shedding, **self._stats}

    # -- placement -----------------------------------------------------------
    def _place(self, exclude=()) -> Optional[str]:
        """Pick the next replica: fresh, not draining/dead, breaker
        willing.  Falls back to probing a stale (but never a draining)
        replica when nothing fresh remains — a router that has lost
        every load report must still try the fleet, not refuse it."""
        now = time.monotonic()
        with self._mu:
            fresh, stale = [], []
            for addr, rep in self._reps.items():
                if addr in exclude or rep["state"] in ("draining", "dead"):
                    continue
                age = now - rep["last_seen"]
                if rep["last_seen"] and age <= self.digest_ttl_s:
                    if rep["state"] == "stale":
                        self._set_state_locked(addr, "up")
                    fresh.append(addr)
                else:
                    if rep["last_seen"] and rep["state"] == "up":
                        # digest TTL: the load report aged out — hold
                        # the replica out of normal placement until a
                        # probe or reply refreshes it
                        self._set_state_locked(addr, "stale")
                    stale.append(addr)
            pool = fresh or stale
            if not pool:
                return None
            if fresh and self.policy == "least_loaded":
                pool = sorted(
                    fresh, key=lambda a:
                    float(self._reps[a]["load"].get("srv_q", 0.0)))
                best_q = float(
                    self._reps[pool[0]]["load"].get("srv_q", 0.0))
                pool = [a for a in pool
                        if float(self._reps[a]["load"].get("srv_q", 0.0))
                        <= best_q]
            self._rr += 1
            candidates = [pool[(self._rr + i) % len(pool)]
                          for i in range(len(pool))]
            for addr in candidates:
                try:
                    self._reps[addr]["breaker"].check(f"fleet.{addr}")
                except _resil.CircuitOpenError:
                    continue
                return addr
            return None

    # -- transport -----------------------------------------------------------
    def _call(self, addr: str, payload: dict, timeout_s: float) -> dict:
        host, _, port = addr.rpartition(":")
        with socket.create_connection((host, int(port)),
                                      timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            send_frame(s, payload)
            return recv_frame(s)

    def _forward(self, payload: dict, timeout_s: float) -> dict:
        """Place + send with bounded re-route.  Idempotent-by-contract:
        the serving ops are pure functions of their payload, so a
        request whose replica died mid-batch is safe to replay on a
        survivor."""
        delays = self._retry.schedule("router.forward")
        deadline = time.monotonic() + (self._retry.deadline_s
                                       or self.request_timeout_s)
        tried: List[str] = []
        last_err: Optional[str] = None
        for attempt in range(self._retry.max_attempts):
            # a replica that failed THIS request is excluded for one
            # lap; after every replica failed once, start a clean lap
            # (the prober may have revived one meanwhile)
            exclude = tried if len(tried) < len(self._reps) else ()
            if len(tried) >= len(self._reps):
                tried = []
            addr = self._place(exclude=exclude)
            if addr is None:
                last_err = "no placeable replica"
                _monitor.FLEET_REROUTE_CTR.inc(1, reason="circuit")
            else:
                try:
                    _resil.maybe_inject("router.forward")
                    resp = self._call(addr, payload, timeout_s)
                    self._note_reply(addr, resp)
                    if resp.get("ok"):
                        return resp
                    err = resp.get("error")
                    if err == "draining":
                        # SIGTERM'd replica: its drain finishes its own
                        # in-flight work; THIS request re-routes
                        self._mark_draining(addr)
                        tried.append(addr)
                        _monitor.FLEET_REROUTE_CTR.inc(1, reason="drain")
                        last_err = f"{addr} draining"
                    elif err == "admission":
                        # a final per-replica verdict — not transport
                        # failure; surface it (router quota is the
                        # fleet-wide gate, this is replica-local)
                        raise AdmissionError(resp.get("detail", err))
                    else:
                        tried.append(addr)
                        _monitor.FLEET_REROUTE_CTR.inc(1, reason="error")
                        last_err = f"{addr}: {err}: " \
                                   f"{resp.get('detail', '')}"
                except (OSError, ConnectionError, ValueError,
                        _resil.InjectedFault) as e:
                    self._mark_dead(addr)
                    tried.append(addr)
                    _monitor.FLEET_REROUTE_CTR.inc(1, reason="dead")
                    last_err = f"{addr}: {e!r}"
            if attempt < self._retry.max_attempts - 1:
                delay = delays[attempt]
                if time.monotonic() + delay > deadline:
                    break
                time.sleep(delay)
        raise FleetError(
            f"fleet request failed after {len(tried) or 1} replica "
            f"attempt(s): {last_err}")

    # -- client surface ------------------------------------------------------
    def _admit(self, tenant: str) -> None:
        with self._mu:
            shedding = self._shedding
        if shedding:
            # the autoscaler's arbitration verdict: cheap, immediate
            # backpressure instead of queueing work that will miss its
            # objective while the spawn warms up
            self.tenants.reject(tenant, "slo_shed")
            with self._mu:
                self._stats["rejected"] += 1
            raise AdmissionError(f"tenant {tenant!r} rejected (slo_shed)")
        if not self.tenants.try_admit(tenant):
            self.tenants.reject(tenant, "quota")
            with self._mu:
                self._stats["rejected"] += 1
            raise AdmissionError(f"tenant {tenant!r} rejected (quota)")
        with self._mu:
            self._stats["admitted"] += 1

    def _finish(self, tenant: str, t0: float, err=None) -> None:
        latency_ms = (time.perf_counter() - t0) * 1e3
        if err is None:
            self.tenants.complete(tenant, latency_ms)
            with self._mu:
                self._stats["completed"] += 1
        else:
            self.tenants.fail(tenant)
            with self._mu:
                self._stats["failed"] += 1
        if self.slo is not None:
            # fleet-level burn signal: every ADMITTED request's e2e
            # outcome (shed/quota rejections never reach here — they
            # must not feed the breach that caused them)
            self.slo.record(tenant, err is None, latency_ms)

    def infer(self, tenant: str, feeds: Dict[str, Any],
              seq_len: Optional[int] = None,
              timeout_s: Optional[float] = None) -> List[Any]:
        """Run one inference request on the fleet; returns the output
        list (nested Python lists, one per fetch)."""
        t0 = time.perf_counter()
        self._admit(tenant)
        payload = {"op": "infer", "tenant": tenant, "seq_len": seq_len,
                   "feeds": {k: {"data": np.asarray(v).tolist(),
                                 "dtype": str(np.asarray(v).dtype)}
                             for k, v in feeds.items()}}
        try:
            resp = self._forward(payload,
                                 timeout_s or self.request_timeout_s)
        except BaseException as e:
            self._finish(tenant, t0, err=e)
            raise
        self._finish(tenant, t0)
        return resp.get("outputs", [])

    def decode(self, tenant: str, prompt: Sequence[int],
               max_new_tokens: int = 16, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None) -> List[int]:
        """Run one decode request on the fleet; returns the token ids."""
        t0 = time.perf_counter()
        self._admit(tenant)
        payload = {"op": "decode", "tenant": tenant,
                   "prompt": [int(t) for t in prompt],
                   "max_new_tokens": int(max_new_tokens),
                   "eos_id": eos_id}
        try:
            resp = self._forward(payload,
                                 timeout_s or self.request_timeout_s)
        except BaseException as e:
            self._finish(tenant, t0, err=e)
            raise
        self._finish(tenant, t0)
        return resp.get("tokens", [])

    # -- prober --------------------------------------------------------------
    def _probe_loop(self) -> None:
        """Background freshness plane: knock on every replica (status
        op) every ttl/3 so an idle fleet stays fresh, a drained replica
        that finished restarting rejoins, and a dead one is probed for
        recovery without waiting for live traffic to find it."""
        interval = max(self.digest_ttl_s / 3.0, 0.05)
        while not self._stop.wait(interval):
            with self._mu:
                addrs = list(self._reps.keys())
            for addr in addrs:
                if self._stop.is_set():
                    return
                try:
                    resp = self._call(addr, {"op": "status"},
                                      timeout_s=min(interval, 2.0))
                    self._note_reply(addr, resp)
                except (OSError, ConnectionError, ValueError):
                    with self._mu:
                        rep = self._reps.get(addr)
                        if rep is not None and rep["state"] != "dead":
                            # no reroute counter here: nothing was
                            # in flight — the probe just downgrades
                            # the table
                            self._set_state_locked(
                                addr,
                                "stale" if rep["state"] == "up"
                                else rep["state"])
