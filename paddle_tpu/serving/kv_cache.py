"""Donated paged KV cache + single-token GPT decode step.

The ``gpt_causal`` decode serving path cannot ride the bucketized batch
server: each generated token would re-attend the whole prefix through a
fresh full-context dispatch (O(T²) per token) and every sequence length
would be a new shape.  Instead the decode engine keeps per-layer K/V pools
of FIXED-SIZE pages (``[L, n_pages, page_len, H, Dh]``), gives each
in-flight request a slot with a page LIST (grown a page at a time, freed
on completion), and jit-compiles ONE step function over the fixed
``[slots]`` batch — requests join and leave the batch between iterations
by flipping their slot's active flag, with no recompile ever.  The pools
are DONATED to each step (``donate_argnums``), so on TPU the update
aliases the input buffers in place; page 0 is a reserved scratch page that
inactive slots write into, keeping the scatter shape static.

The step math mirrors ``models/transformer.build_gpt_pretrain`` op by op
(arange positions, pre-encoder LN, fused-QKV post-LN blocks, erf-gelu FFN,
f32 LN/softmax stats) so the engine's logits match the training program's
within float tolerance — regression-tested against the full-context
program in tests/test_serving.py.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor

KV_PAGES_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_serving_kv_pages_in_use",
    "KV-cache pages currently owned by in-flight decode requests "
    "(page 0, the inactive-slot scratch page, is never owned)")
KV_ALLOC_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_serving_kv_page_events_total",
    "KV page pool events", ("event",))
_ALLOC = KV_ALLOC_CTR.labels(event="alloc")
_FREE = KV_ALLOC_CTR.labels(event="free")
_EXHAUSTED = KV_ALLOC_CTR.labels(event="exhausted")


class PagedKVCache:
    """Fixed-size page pool for one decode engine.

    Host side: a free-page list and per-slot page lists (``alloc_page`` /
    ``free_slot``).  Device side: the stacked K/V pools the jitted step
    donates and returns.  Page 0 is reserved scratch — inactive slots'
    writes land there, so the step's scatter indices never change shape.
    """

    def __init__(self, n_layers: int, n_pages: int, page_len: int,
                 n_head: int, d_head: int, max_slots: int,
                 dtype=jnp.float32):
        if n_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        self.n_layers = int(n_layers)
        self.n_pages = int(n_pages)
        self.page_len = int(page_len)
        self.max_slots = int(max_slots)
        shape = (n_layers, n_pages, page_len, n_head, d_head)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self._mu = threading.Lock()
        self._free: List[int] = list(range(1, n_pages))  # guarded-by: _mu
        self._owned: Dict[int, List[int]] = {}  # guarded-by: _mu
        # the pool's device bytes are attributed to the HBM accountant's
        # kv_pages class (weak registration — telemetry must not keep a
        # dead engine's pools alive)
        from .. import hbm as _hbm
        _hbm.register_kv_pool(self)

    def alloc_page(self, slot: int) -> Optional[int]:
        """Grant ``slot`` one more page; None when the pool is exhausted
        (the caller parks the request until a completion frees pages)."""
        with self._mu:
            if not self._free:
                _EXHAUSTED.inc()
                return None
            page = self._free.pop()
            self._owned.setdefault(slot, []).append(page)
            in_use = self.n_pages - 1 - len(self._free)
        _ALLOC.inc()
        KV_PAGES_GAUGE.set(in_use)
        return page

    def free_slot(self, slot: int) -> int:
        """Return every page ``slot`` owns to the pool (request complete);
        returns how many were freed.  The page CONTENTS are not cleared —
        the next owner overwrites positions before attending them, and
        the attention mask hides everything past the written prefix."""
        with self._mu:
            pages = self._owned.pop(slot, [])
            self._free.extend(pages)
            in_use = self.n_pages - 1 - len(self._free)
        if pages:
            _FREE.inc(len(pages))
            KV_PAGES_GAUGE.set(in_use)
        return len(pages)

    def pages_in_use(self) -> int:
        with self._mu:
            return self.n_pages - 1 - len(self._free)

    def buffers_alive(self) -> bool:
        """False when a failed donated step consumed the pools (the
        arguments were donated to a call that died mid-execution)."""
        k = self.k
        return not (hasattr(k, "is_deleted") and k.is_deleted())

    def reinit_pools(self) -> None:
        """Fresh zero pools after a failed donated step poisoned the old
        buffers (shape/dtype metadata survives deletion).  Cached
        prefixes are gone, so the caller must fail every in-flight
        request first; page bookkeeping stays valid."""
        shape, dtype = self.k.shape, self.k.dtype
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)

    def pages_of(self, slot: int) -> List[int]:
        with self._mu:
            return list(self._owned.get(slot, []))

    def pool_bytes(self) -> int:
        """Device bytes of the K/V pools (both stacks) — the resident
        cost of the cache regardless of page occupancy."""
        return (int(getattr(self.k, "nbytes", 0) or 0)
                + int(getattr(self.v, "nbytes", 0) or 0))


def params_from_scope(scope, cfg) -> Dict[str, jnp.ndarray]:
    """Pull the GPT parameter set (models/transformer naming) out of a
    scope holding a trained/initialized ``build_gpt_pretrain`` model."""
    names = ["word_embedding", "pos_embedding", "pre_encoder.ln.w",
             "pre_encoder.ln.b", "lm_out.w", "lm_out.b"]
    for i in range(cfg.n_layer):
        p = f"enc_{i}"
        names += [f"{p}.attn.qkv.w", f"{p}.attn.qkv.b",
                  f"{p}.attn.out.w", f"{p}.attn.out.b",
                  f"{p}.ln1.w", f"{p}.ln1.b",
                  f"{p}.ffn.fc1.w", f"{p}.ffn.fc1.b",
                  f"{p}.ffn.fc2.w", f"{p}.ffn.fc2.b",
                  f"{p}.ln2.w", f"{p}.ln2.b"]
    params = {}
    for n in names:
        v = scope.find_var(n)
        if v is None:
            raise KeyError(
                f"GPT decode param {n!r} missing from scope — build the "
                "model with models.transformer.build_gpt_pretrain and run "
                "the startup program first")
        params[n] = jnp.asarray(v)
    return params


def _layer_norm(x, w, b, eps=1e-5):
    # mirrors ops/nn_ops._layer_norm: stats in f32, affine in x dtype
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(v + eps)
    y = (x - m.astype(x.dtype)) * inv.astype(x.dtype)
    return (y * w.astype(y.dtype) + b.astype(y.dtype)).astype(x.dtype)


class GPTDecodeModel:
    """One-token-per-slot decode step over the paged cache, jitted once.

    ``step(params, k, v, ids, pos, page_table, active)`` processes the
    current token of every slot: writes this position's K/V into the
    slot's page, attends the slot's whole cached prefix (pages gathered
    by the table, positions past ``pos`` masked), and returns the
    next-token logits.  All shapes are fixed by (max_slots, max_pages,
    page_len), so the first call traces+compiles and every later call —
    whatever mix of requests occupies the slots — is a cache hit
    (``trace_count`` stays flat; asserted in tests).  K/V pools are
    donated: argument buffers are reused for the results on backends
    that support donation.
    """

    def __init__(self, cfg, page_len: int, max_pages: int):
        self.cfg = cfg
        self.page_len = int(page_len)
        self.max_pages = int(max_pages)
        self.n_head = cfg.n_head
        self.d_head = cfg.d_model // cfg.n_head
        self.trace_count = 0
        self._step = jax.jit(self._step_impl, donate_argnums=(1, 2))

    def kv_shape(self, n_pages: int):
        return (self.cfg.n_layer, n_pages, self.page_len, self.n_head,
                self.d_head)

    def step(self, params, k, v, ids, pos, page_table, active):
        """ids/pos/active: [S] int32/bool; page_table: [S, max_pages]
        int32 (unallocated entries 0 — masked off by ``pos``).
        Returns (logits [S, vocab], new_k, new_v)."""
        return self._step(params, k, v, jnp.asarray(ids, jnp.int32),
                          jnp.asarray(pos, jnp.int32),
                          jnp.asarray(page_table, jnp.int32),
                          jnp.asarray(active, bool))

    def _step_impl(self, params, k, v, ids, pos, page_table, active):
        # python side effect on purpose: runs only while TRACING, so the
        # counter counts compiles — the "no per-request recompile" gate
        self.trace_count += 1
        cfg = self.cfg
        S = ids.shape[0]
        H, Dh, D = self.n_head, self.d_head, cfg.d_model
        PL, MP = self.page_len, self.max_pages
        T = MP * PL                      # max attended context per slot
        scale = float(Dh) ** -0.5

        x = params["word_embedding"][ids] + params["pos_embedding"][pos]
        x = _layer_norm(x, params["pre_encoder.ln.w"],
                        params["pre_encoder.ln.b"])

        # this token's write target: (page, offset) per slot; inactive
        # slots are routed to scratch page 0 so the scatter stays dense
        page_idx = pos // PL
        offset = pos % PL
        cur_page = jnp.take_along_axis(
            page_table, page_idx[:, None], axis=1)[:, 0]
        cur_page = jnp.where(active, cur_page, 0)

        # context mask: position t of the gathered pages is attendable
        # iff t <= pos (page-table order IS position order)
        t_idx = jnp.arange(T)
        attend = t_idx[None, :] <= pos[:, None]          # [S, T]
        neg = jnp.asarray(-1e9, x.dtype)

        for i in range(cfg.n_layer):
            p = f"enc_{i}"
            qkv = x @ params[f"{p}.attn.qkv.w"] + params[f"{p}.attn.qkv.b"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(S, H, Dh)
            k_new = k_new.reshape(S, H, Dh)
            v_new = v_new.reshape(S, H, Dh)
            k = k.at[i, cur_page, offset].set(k_new)
            v = v.at[i, cur_page, offset].set(v_new)
            # gather this slot's prefix: [S, MP, PL, H, Dh] -> [S, T, H, Dh]
            kp = k[i][page_table].reshape(S, T, H, Dh)
            vp = v[i][page_table].reshape(S, T, H, Dh)
            scores = jnp.einsum("shd,sthd->sht", q, kp) * scale
            scores = jnp.where(attend[:, None, :], scores, neg)
            w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            w = w.astype(x.dtype)
            ctx = jnp.einsum("sht,sthd->shd", w, vp).reshape(S, D)
            attn = ctx @ params[f"{p}.attn.out.w"] + \
                params[f"{p}.attn.out.b"]
            x = _layer_norm(x + attn, params[f"{p}.ln1.w"],
                            params[f"{p}.ln1.b"])
            h = x @ params[f"{p}.ffn.fc1.w"] + params[f"{p}.ffn.fc1.b"]
            h = jax.nn.gelu(h, approximate=False)
            ffn = h @ params[f"{p}.ffn.fc2.w"] + params[f"{p}.ffn.fc2.b"]
            x = _layer_norm(x + ffn, params[f"{p}.ln2.w"],
                            params[f"{p}.ln2.b"])

        logits = x @ params["lm_out.w"] + params["lm_out.b"]
        return logits, k, v


class DecodeEngine:
    """Ties the model step to the page pool for the decode scheduler.

    Holds the donated device pools, the host page tables, and per-slot
    cursors; the scheduler drives :meth:`run_iteration` with whatever
    requests currently occupy slots.  Greedy (argmax) decoding — the
    serving contract this PR needs; sampling strategies are a follow-on.
    """

    def __init__(self, cfg, params_or_scope, max_slots: int = 4,
                 page_len: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 program=None):
        from ..flags import get_flags
        fl = get_flags(["FLAGS_serving_kv_page_len",
                        "FLAGS_serving_kv_pages"])
        if program is not None:
            # static GSPMD-serving gate (analysis.sharding): the paged
            # pools below host full per-head pages and full unsharded
            # params on ONE chip, so a model-parallel-sharded decode
            # program is refused HERE, naming its offending specs,
            # instead of producing silently-wrong gathers at step time
            from ..analysis.sharding import check_decode_hostable
            check_decode_hostable(program)
        self.cfg = cfg
        self.page_len = int(page_len or fl["FLAGS_serving_kv_page_len"])
        self.max_seq = int(max_seq or cfg.max_pos)
        self.max_pages = -(-self.max_seq // self.page_len)  # ceil div
        self.max_slots = int(max_slots)
        n_pages = int(n_pages or fl["FLAGS_serving_kv_pages"]) or \
            (1 + self.max_slots * self.max_pages)
        if hasattr(params_or_scope, "find_var"):
            self.params = params_from_scope(params_or_scope, cfg)
        else:
            self.params = {n: jnp.asarray(a)
                           for n, a in dict(params_or_scope).items()}
        self.model = GPTDecodeModel(cfg, self.page_len, self.max_pages)
        self.cache = PagedKVCache(
            cfg.n_layer, n_pages, self.page_len, cfg.n_head,
            cfg.d_model // cfg.n_head, self.max_slots)
        # host-side page table mirror fed to every step
        self.page_table = np.zeros((self.max_slots, self.max_pages),
                                   np.int32)

    @property
    def trace_count(self) -> int:
        return self.model.trace_count

    def reserve_slot(self, slot: int, n_pages: int) -> bool:
        """Allocate a request's WORST-CASE page count up front (rolled
        back on shortfall).  Admission-time reservation is what makes
        the decode loop deadlock-free: two optimistically-admitted
        requests could otherwise each stall on the other's unreleased
        pages mid-growth — and completions happen on the same thread
        that would be stalling, so nothing would ever free them."""
        if n_pages > self.max_pages:
            return False
        got = []
        for _ in range(n_pages):
            p = self.cache.alloc_page(slot)
            if p is None:
                self.cache.free_slot(slot)   # roll back the partial grab
                self.page_table[slot, :] = 0
                return False
            got.append(p)
        for i, p in enumerate(got):
            self.page_table[slot, i] = p
        return True

    def ensure_page(self, slot: int, pos: int) -> bool:
        """Make sure the page covering ``pos`` exists for ``slot``;
        False when the pool is exhausted (caller defers the request)."""
        need = pos // self.page_len
        if need >= self.max_pages:
            return False         # past the engine's max context window
        owned = len(self.cache.pages_of(slot))
        while owned <= need:
            page = self.cache.alloc_page(slot)
            if page is None:
                return False
            self.page_table[slot, owned] = page
            owned += 1
        return True

    def release_slot(self, slot: int) -> None:
        self.cache.free_slot(slot)
        self.page_table[slot, :] = 0

    def run_iteration(self, ids, pos, active):
        """One decode step over all slots; returns logits [S, vocab]
        (host numpy) after updating the donated pools."""
        logits, self.cache.k, self.cache.v = self.model.step(
            self.params, self.cache.k, self.cache.v, ids, pos,
            self.page_table, active)
        return np.asarray(logits)
