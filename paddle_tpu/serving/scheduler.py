"""Continuous-batching scheduler over the async executor.

Two execution loops, one admission contract:

- :class:`ContinuousBatcher` (stateless request/response models): an
  admission queue drained by a scheduler thread that coalesces queued
  requests into the widest same-bucket batch available (waiting at most
  ``FLAGS_serving_batch_wait_ms`` for stragglers), pads the batch to the
  bucket's fixed (width, seq) shape, and dispatches through
  ``Executor.run(..., return_numpy=False)`` — the PR-1 lazy-fetch path, so
  host batch assembly of request *i+1* overlaps device execution of *i*
  and ``FLAGS_executor_max_inflight_steps`` bounds run-ahead.  A separate
  completion thread materializes fetch handles, slices each request's rows
  back out (padding trimmed), and resolves futures.

- :class:`DecodeScheduler` (``gpt_causal`` token generation): drives the
  :class:`~paddle_tpu.serving.kv_cache.DecodeEngine` — each iteration runs
  ONE compiled step over the fixed slot batch; requests join a free slot
  (prefill consumes prompt tokens one per iteration through the same
  step), leave on EOS/max-tokens (pages freed), and the batch composition
  changes every iteration with zero recompiles.

Dispatch faults that are transient (``FLAGS_fault_inject`` fires,
infra errors tagged via ``resilience.mark_transient``) are ABSORBED: the
batch re-dispatches up to ``FLAGS_serving_max_retries`` times before the
batch's requests fail — counted in
``paddle_tpu_serving_faults_absorbed_total``.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import monitor as _monitor
from ..framework.executor import last_step_id
from .bucketing import PAD_TOKENS_CTR

OCCUPANCY_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_serving_batch_occupancy",
    "real requests per dispatched batch/decode iteration (mean > 1 == "
    "continuous batching is actually coalescing), by mode: 'batch' for "
    "the coalescing batcher, 'decode' for the KV decode loop — a "
    "process running both must not blend them in per-server views",
    labelnames=("mode",),
    buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 64.0))
BATCHES_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_serving_batches_total",
    "dispatched serving batches / decode iterations, by bucket "
    "(bucket='decode' for the KV-cache loop)", ("bucket",))
FAULTS_ABSORBED_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_serving_faults_absorbed_total",
    "transient dispatch faults absorbed by a batch re-dispatch "
    "(requests completed anyway)")

#: wall clock of the most recent scheduler-loop wake (batcher dispatch
#: or decode iteration) — the liveness proof behind the srv_q/occ/
#: slots/tps digest keys' FLAGS_fleet_digest_ttl_s aging
#: (monitor._serving_digest_fresh).  Liveness, not traffic: the idle
#: loops wake on their bounded waits and keep touching this, while a
#: scheduler wedged inside a dispatch stops — and its replica ages out
#: of router placement.  Benign-race float: single word, newest wins.
last_alive_wall = 0.0


def _touch_alive() -> None:
    global last_alive_wall
    last_alive_wall = time.time()

#: per-process request trace ids: every admitted request gets one, and
#: every phase span of its lifetime carries it — `trace` in the span
#: args groups the chain admission->materialize in the exported ring
_TRACE_IDS = itertools.count(1)


def _emit_request_trace(req: "Request", phases, e2e_ms: float,
                        bucket=None, extra=None) -> None:
    """Emit the request's phase spans (each tagged with its trace id,
    tenant, and bucket) into the tracer ring and the per-phase latency
    histograms.  ``phases`` is an ordered list of (name, t0, t1)
    perf_counter boundaries that PARTITION submit->resolve, so the
    per-phase sum reconstructs the measured end-to-end latency (the
    serving_smoke 10% gate).  ``extra`` maps phase name -> extra span
    args (the dispatch phase carries the process-global step id, batch
    width/occupancy, and the padding overhead)."""
    bucket = str(req.bucket if bucket is None else bucket)
    tenant = str(req.tenant)
    tracer = _monitor.TRACER
    for name, t0, t1 in phases:
        if t0 is None or t1 is None or t1 < t0:
            continue
        _monitor.SERVING_PHASE_HIST.observe(
            (t1 - t0) * 1e3, phase=name, tenant=tenant, bucket=bucket)
        if tracer.enabled:
            args = {"trace": req.trace_id, "tenant": tenant,
                    "bucket": bucket}
            if name == "materialize":
                # the request's measured e2e rides the LAST span of the
                # chain, so an offline reader can check the phase sum
                # against it without any out-of-band ledger
                args["e2e_ms"] = round(e2e_ms, 3)
            if extra and name in extra:
                args.update(extra[name])
            tracer.add_complete("serving." + name, "serving", t0, t1,
                                args)


class ServingFuture:
    """Resolution handle for one request (threading.Event based)."""

    def __init__(self):
        self._ev = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _resolve(self, result) -> None:
        self._result = result
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("serving request still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class Request:
    """One admitted request: per-example feeds (no batch dim) + future."""

    __slots__ = ("tenant", "feeds", "seq_len", "bucket", "future",
                 "t_submit", "prompt", "max_new_tokens", "eos_id",
                 "admit_gen", "trace_id", "tm")

    def __init__(self, tenant: str, feeds: Optional[Dict[str, Any]] = None,
                 seq_len: int = 0, bucket: int = 0,
                 prompt=None, max_new_tokens: int = 0,
                 eos_id: Optional[int] = None):
        self.tenant = tenant
        self.feeds = feeds
        self.seq_len = seq_len
        self.bucket = bucket
        self.future = ServingFuture()
        self.t_submit = time.perf_counter()
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.admit_gen = 0   # tenant incarnation at admission (server)
        self.trace_id = next(_TRACE_IDS)
        # phase boundary marks (perf_counter): written strictly along
        # the request's pipeline handoffs (submit thread -> scheduler
        # thread -> completion thread), each handoff through a lock, so
        # readers always see the marks of the phases that finished
        self.tm: Dict[str, float] = {"submit": self.t_submit}


class ContinuousBatcher:
    """Bucket-coalescing scheduler + completion pipeline (batch mode)."""

    def __init__(self, executor, scope, bucket_plan, on_complete,
                 on_fail, max_retries: int = 1, batch_wait_ms: float = 0.0):
        self._exe = executor
        self._scope = scope
        self._plan = bucket_plan
        self._on_complete = on_complete      # (request, result, latency_ms)
        self._on_fail = on_fail              # (request, exception)
        self._max_retries = int(max_retries)
        self._wait_s = max(0.0, float(batch_wait_ms)) / 1e3
        self._cv = threading.Condition()
        self._queue: collections.deque = collections.deque()  # guarded-by: _cv
        self._pending = 0          # admitted, not yet resolved  # guarded-by: _cv
        self._stop = False         # guarded-by: _cv
        self._done_cv = threading.Condition()
        self._done_q: collections.deque = \
            collections.deque()    # guarded-by: _done_cv
        self._done_stop = False    # guarded-by: _done_cv
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        for name, fn in (("serving-scheduler", self._schedule_loop),
                         ("serving-completion", self._complete_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Stop accepting work; both threads exit after finishing what is
        already queued/in flight (the scheduler drains the queue, then
        its exit releases the completion thread — never the reverse, so
        a dispatched batch's futures always resolve)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def enqueue(self, req: Request) -> bool:
        """False when the scheduler has been stopped — nothing would ever
        pop the queue, so the caller must fail the request instead of
        stranding its future (enqueue racing stop())."""
        with self._cv:
            if self._stop:
                return False
            req.tm["enq"] = time.perf_counter()
            self._queue.append(req)
            self._pending += 1
            self._cv.notify()
        return True

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until every admitted request has resolved (completed or
        failed) — the SIGTERM graceful-drain barrier.  False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.1))
        return True

    # -- scheduler thread ----------------------------------------------------
    def _take_batch(self) -> Optional[List[Request]]:
        """Pop the widest same-bucket batch available, coalescing-wait up
        to the window for stragglers; None on stop with an empty queue."""
        with self._cv:
            while not self._queue and not self._stop:
                _touch_alive()
                self._cv.wait(0.1)
            _touch_alive()
            if not self._queue:
                return None
            bucket = self._queue[0].bucket
        # resolve the bucket plan OUTSIDE the queue lock: a cold bucket
        # builds a program + HBM plan here, and submitters must not
        # block behind it.  Only this scheduler thread pops, so the
        # peeked head cannot be stolen meanwhile.  A factory/build error
        # fails that bucket's queued requests — not this thread (a dead
        # scheduler would strand every later future forever).
        try:
            width = self._plan.plan(bucket)[3]
        except Exception as e:
            with self._cv:
                bad = self._pop_bucket_locked(bucket, len(self._queue))
            self._fail_batch(bad, e)
            return []
        with self._cv:
            deadline = time.monotonic() + self._wait_s
            batch = self._pop_bucket_locked(bucket, width)
            while len(batch) < width and not self._stop:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
                batch.extend(
                    self._pop_bucket_locked(bucket, width - len(batch)))
            return batch

    def _pop_bucket_locked(self, bucket: int, n: int) -> List[Request]:
        # guarded-by-caller: _cv
        out: List[Request] = []
        if n <= 0:
            return out
        now = time.perf_counter()
        keep: collections.deque = collections.deque()
        while self._queue:
            r = self._queue.popleft()
            if r.bucket == bucket and len(out) < n:
                r.tm["pop"] = now        # queue_wait ends here
                out.append(r)
            else:
                keep.append(r)
        self._queue.extend(keep)
        return out

    def _schedule_loop(self) -> None:
        try:
            self._schedule_loop_inner()
        finally:
            # the completion thread exits only AFTER this thread: a
            # stop() racing an in-flight batch must let the completion
            # side drain everything the scheduler ever appended, or the
            # batch's futures would strand un-resolved
            with self._done_cv:
                self._done_stop = True
                self._done_cv.notify_all()

    def _schedule_loop_inner(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if not batch:
                continue             # bucket-plan failure already handled
            bucket = batch[0].bucket
            try:
                compiled, feed_names, fetch_names, width = \
                    self._plan.plan(bucket)
                feed = self._assemble(batch, bucket, feed_names, width,
                                      compiled.program)
            except Exception as e:
                # a malformed request (missing feed key, oversize or
                # ragged array) must fail ITS batch, never kill this
                # thread — a dead scheduler would strand every later
                # request's future forever
                self._fail_batch(batch, e)
                continue
            PAD_TOKENS_CTR.inc(width - len(batch))
            t_d0 = time.perf_counter()
            handles = self._dispatch(compiled, feed, fetch_names, batch)
            t_d1 = time.perf_counter()
            BATCHES_CTR.inc(1, bucket=str(bucket))
            OCCUPANCY_HIST.observe(float(len(batch)), mode="batch")
            _monitor.SERVING_LAST_OCC_GAUGE.set(float(len(batch)))
            if handles is None:
                continue                     # batch failed; futures done
            # correlation hint: the step id the executor just stamped on
            # its executor.dispatch span + StepTraceAnnotation — this
            # scheduler thread dispatched it, so reading it here (before
            # any other run() of ours) names OUR step
            meta = {"t_d0": t_d0, "t_d1": t_d1, "step": last_step_id(),
                    "width": width, "occupancy": len(batch)}
            with self._done_cv:
                self._done_q.append((batch, handles, bucket, meta))
                self._done_cv.notify()

    @staticmethod
    def _assemble(batch, bucket, feed_names, width, program):
        """Padded fixed-shape batch feed from the requests' per-example
        arrays (raises on malformed requests — caller fails the batch).
        The BUCKET PROGRAM's declared var shapes say which feeds carry
        the sequence axis: only feeds declared at the bucket length are
        padded; fixed-length feeds (a static feature vector) stack as-is
        and a mismatch fails the batch loudly instead of smuggling a
        wrong shape into a fresh compile."""
        from .bucketing import pad_to_bucket
        block = program.global_block()
        feed = {}
        for name in feed_names:
            declared = tuple(block.var(name).shape or ()) \
                if block.has_var(name) else ()
            is_seq = len(declared) > 1 and declared[1] == bucket
            rows = [pad_to_bucket(r.feeds[name], bucket) if is_seq
                    else np.asarray(r.feeds[name]) for r in batch]
            a = np.stack(rows)
            if len(batch) < width:           # fixed-shape dummy rows
                a = np.pad(a, [(0, width - len(batch))] +
                           [(0, 0)] * (a.ndim - 1))
            feed[name] = a
        return feed

    def _dispatch(self, compiled, feed, fetch_names, batch):
        """Run the batch; transient faults re-dispatch up to the retry
        budget (injected-fault absorption), anything else — or an
        exhausted budget — fails the batch's futures."""
        from .. import resilience as _resil
        attempt = 0
        while True:
            try:
                # watchdog-watched: a dispatch hung past
                # FLAGS_watchdog_timeout_s dumps all stacks and raises
                # HungStepError here — non-transient, so it falls through
                # to _fail_batch instead of silently stalling the queue
                with _resil.WATCHDOG.watch("serving.batch_dispatch"):
                    _resil.maybe_inject("serving.batch_dispatch")
                    return self._exe.run(
                        compiled, feed=feed, fetch_list=list(fetch_names),
                        scope=self._scope, return_numpy=False)
            except Exception as e:
                if _resil.is_transient(e) and attempt < self._max_retries:
                    attempt += 1
                    FAULTS_ABSORBED_CTR.inc()
                    if _monitor.TRACER.enabled:
                        _monitor.TRACER.instant(
                            "serving.fault_absorbed", "serving",
                            {"attempt": attempt, "error": repr(e)[:120]})
                    continue
                self._fail_batch(batch, e)
                return None

    def _fail_batch(self, batch, err) -> None:
        for r in batch:
            self._on_fail(r, err)
        with self._cv:
            self._pending -= len(batch)
            self._cv.notify_all()

    # -- completion thread ---------------------------------------------------
    def _complete_loop(self) -> None:
        while True:
            with self._done_cv:
                while not self._done_q:
                    if self._done_stop:
                        return
                    self._done_cv.wait(0.1)
                batch, handles, bucket, meta = self._done_q.popleft()
            try:
                # materialize AND slice before resolving anything: a
                # failure here (async device error, unexpected fetch
                # rank) fails the whole batch's futures instead of
                # killing this thread with some futures half-resolved
                outs = [np.asarray(h) for h in handles]
                results = []
                for i, r in enumerate(batch):
                    result = []
                    for a in outs:
                        row = a[i]
                        if (row.ndim >= 1 and row.shape[0] == bucket
                                and r.seq_len != bucket):
                            row = row[:r.seq_len]  # trim bucket padding
                        result.append(row)
                    results.append(result)
            except Exception as e:
                self._fail_batch(batch, e)
                continue
            now = time.perf_counter()
            pad = meta["width"] - meta["occupancy"]
            dispatch_args = {
                "step": meta["step"], "width": meta["width"],
                "occupancy": meta["occupancy"], "pad_rows": pad,
                "pad_frac": round(pad / float(meta["width"]), 4)}
            for r, result in zip(batch, results):
                e2e_ms = (now - r.t_submit) * 1e3
                _emit_request_trace(r, (
                    ("admit", r.tm.get("submit"), r.tm.get("enq")),
                    ("queue_wait", r.tm.get("enq"), r.tm.get("pop")),
                    ("batch_wait", r.tm.get("pop"), meta["t_d0"]),
                    ("dispatch", meta["t_d0"], meta["t_d1"]),
                    ("materialize", meta["t_d1"], now),
                ), e2e_ms, extra={"dispatch": dispatch_args})
                self._on_complete(r, result, e2e_ms)
            with self._cv:
                self._pending -= len(batch)
                self._cv.notify_all()


class _SlotState:
    __slots__ = ("req", "tokens", "pos", "generated", "iters")

    def __init__(self, req: Request):
        self.req = req
        self.tokens: List[int] = [int(t) for t in np.asarray(
            req.prompt).ravel()]
        self.pos = 0
        self.generated: List[int] = []
        self.iters = 0          # decode iterations this request rode


class DecodeScheduler:
    """Continuous-batching loop over the paged-KV decode engine.

    One thread, one compiled step: every iteration admits queued requests
    into free slots, feeds each active slot its current token (prompt
    token during prefill, previous argmax during generation), and retires
    slots whose request hit EOS / max_new_tokens — freeing their pages
    for the next request with the compile counter flat."""

    def __init__(self, engine, on_complete, on_fail,
                 max_retries: int = 1):
        self._engine = engine
        self._on_complete = on_complete
        self._on_fail = on_fail
        self._max_retries = int(max_retries)
        self._cv = threading.Condition()
        self._queue: collections.deque = collections.deque()  # guarded-by: _cv
        self._pending = 0   # guarded-by: _cv
        self._stop = False  # guarded-by: _cv
        self._slots: List[Optional[_SlotState]] = \
            [None] * engine.max_slots
        self._thread: Optional[threading.Thread] = None
        self._iter = 0                 # decode-loop iterations (loop thread only)
        #: trailing (t, n_generated) window for the tokens/s gauge —
        #: touched only by the decode thread
        self._tok_win: collections.deque = collections.deque()
        #: per-tenant KV-page ownership — admits happen in _admit_locked
        #: and releases in _retire/_run_step, all on the decode thread,
        #: so no lock; statusz readers go through kv_census() which
        #: snapshots the slot list
        self._tenant_pages: Dict[str, int] = {}

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="serving-decode", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def enqueue(self, req: Request) -> bool:
        """False when the decode loop has been stopped (see
        :meth:`ContinuousBatcher.enqueue`)."""
        with self._cv:
            if self._stop:
                return False
            req.tm["enq"] = time.perf_counter()
            self._queue.append(req)
            self._pending += 1
            self._cv.notify()
        return True

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def drain(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.1))
        return True

    # -- decode loop ---------------------------------------------------------
    def _admit_locked(self) -> None:
        # guarded-by-caller: _cv
        for s, state in enumerate(self._slots):
            if state is not None or not self._queue:
                continue
            req = self._queue[0]
            # reserve the request's WORST-CASE pages now: admission is
            # the only safe wait point (completions run on this same
            # thread, so a mid-flight page stall could never resolve)
            need = -(-(int(np.asarray(req.prompt).size)
                       + req.max_new_tokens) // self._engine.page_len)
            if not self._engine.reserve_slot(s, max(1, need)):
                break               # pool exhausted: wait for completions
            self._queue.popleft()
            req.tm["slot"] = time.perf_counter()   # queue_wait ends
            self._slots[s] = _SlotState(req)
            self._kv_account(req.tenant,
                             len(self._engine.cache.pages_of(s)),
                             reserved=True)

    def _loop(self) -> None:
        eng = self._engine
        S = eng.max_slots
        while True:
            _touch_alive()
            with self._cv:
                self._admit_locked()
                active_slots = [s for s in range(S)
                                if self._slots[s] is not None]
                if not active_slots:
                    if self._stop:
                        return
                    self._cv.wait(0.1)
                    continue
            ids = np.zeros(S, np.int32)
            pos = np.zeros(S, np.int32)
            active = np.zeros(S, bool)
            stepped = []
            for s in active_slots:
                st = self._slots[s]
                # the page covering this position must exist BEFORE the
                # step writes into it; an exhausted pool parks the slot
                # for this iteration (completions will free pages)
                if not eng.ensure_page(s, st.pos):
                    continue
                ids[s] = st.tokens[st.pos]
                pos[s] = st.pos
                active[s] = True
                stepped.append(s)
            if not stepped:
                time.sleep(0.001)
                continue
            _monitor.SERVING_FREE_SLOTS_GAUGE.set(
                float(S - len(active_slots)))
            self._iter += 1
            t_i0 = time.perf_counter()
            logits = self._run_step(ids, pos, active, stepped)
            t_i1 = time.perf_counter()
            if _monitor.TRACER.enabled:
                _monitor.TRACER.add_complete(
                    "serving.decode_iter", "serving", t_i0, t_i1,
                    {"iter": self._iter, "occupancy": len(stepped)})
            if logits is None:
                continue
            self._logits_sentinel(logits, stepped)
            BATCHES_CTR.inc(1, bucket="decode")
            OCCUPANCY_HIST.observe(float(len(stepped)), mode="decode")
            _monitor.SERVING_LAST_OCC_GAUGE.set(float(len(stepped)))
            now = time.perf_counter()
            n_gen = 0
            for s in stepped:
                st = self._slots[s]
                st.pos += 1
                st.iters += 1
                if st.pos < len(st.tokens):
                    continue                   # prefill: next prompt token
                nxt = int(np.argmax(logits[s]))
                st.tokens.append(nxt)
                st.generated.append(nxt)
                n_gen += 1
                done = (len(st.generated) >= st.req.max_new_tokens
                        or (st.req.eos_id is not None
                            and nxt == st.req.eos_id)
                        or st.pos + 1 >= eng.max_seq)
                if done:
                    self._retire(s, st, now)
            self._update_token_rate(now, n_gen)

    def _run_step(self, ids, pos, active, stepped):
        from .. import resilience as _resil
        attempt = 0
        while True:
            try:
                # watchdog-watched like the batcher's dispatch: a hung
                # decode iteration dumps stacks and fails its requests
                with _resil.WATCHDOG.watch("serving.decode_step"):
                    _resil.maybe_inject("serving.decode_step")
                    return self._engine.run_iteration(ids, pos, active)
            except Exception as e:
                # retry only while the donated pools survived the
                # failure: a fault from INSIDE the jitted step consumed
                # the k/v buffers, and re-invoking with deleted arrays
                # would just fail differently — fail the requests and
                # rebuild the pools instead
                alive = self._engine.cache.buffers_alive()
                if (alive and _resil.is_transient(e)
                        and attempt < self._max_retries):
                    attempt += 1
                    FAULTS_ABSORBED_CTR.inc()
                    continue
                # every active slot's cached prefix rides those pools —
                # all of them are lost, not just this iteration's set
                failed = [s for s in range(len(self._slots))
                          if self._slots[s] is not None] \
                    if not alive else list(stepped)
                for s in failed:
                    st = self._slots[s]
                    self._kv_account(
                        st.req.tenant,
                        -len(self._engine.cache.pages_of(s)))
                    self._engine.release_slot(s)
                    self._slots[s] = None
                    self._on_fail(st.req, e)
                if not alive:
                    self._engine.cache.reinit_pools()
                with self._cv:
                    self._pending -= len(failed)
                    self._cv.notify_all()
                return None

    def _logits_sentinel(self, logits, stepped) -> None:
        """Decode-path numerics sentinel (behind ``FLAGS_numerics``): a
        non-finite logit means the model/KV state is poisoned and every
        argmax downstream of it is garbage — count it per class
        ('logits') and emit ONE anomaly record per episode.  The logits
        are already host-side at argmax time, so the scan costs one
        vectorized pass, no device sync."""
        try:
            from ..analysis import numerics as _numerics
            if _numerics.mode() == "off":
                return
            sub = logits[stepped] if len(stepped) < logits.shape[0] \
                else logits
            bad = int(sub.size - np.count_nonzero(np.isfinite(sub)))
            _numerics.note_nonfinite(
                "logits", bad, step=self._iter,
                detail={"slots": list(map(int, stepped))} if bad
                else None)
        except Exception:
            pass            # the sentinel must never fail a decode step

    def _kv_account(self, tenant, delta: int, reserved: bool = False) -> None:
        """Per-tenant KV-page bookkeeping (decode thread only): the
        occupancy gauge tracks pages currently owned by the tenant's
        requests, and each admission's reservation bumps the cumulative
        counter — both fold on tenant eviction through
        ``monitor.retire_tenant_series`` (PR-2 semantics), so a
        revolving tenant population cannot grow the registry while
        ``counter_totals()`` stays exact."""
        tenant = str(tenant)
        total = max(self._tenant_pages.get(tenant, 0) + int(delta), 0)
        self._tenant_pages[tenant] = total
        _monitor.SERVING_KV_TENANT_PAGES.set(float(total), tenant=tenant)
        if total == 0:
            # no pages -> no fragmentation: the frag gauge is otherwise
            # written only by kv_census() scrapes and would freeze at
            # the last in-flight value after the tenant's requests retire
            _monitor.SERVING_KV_TENANT_FRAG.set(0.0, tenant=tenant)
        if reserved and delta > 0:
            _monitor.SERVING_KV_TENANT_ALLOC_CTR.inc(int(delta),
                                                     tenant=tenant)

    def kv_census(self) -> Dict[str, dict]:
        """Per-tenant KV-page occupancy + internal fragmentation (the
        /statusz memory section): for every in-flight request, pages
        owned vs positions actually written — ``frag = 1 - written /
        (pages * page_len)`` is the reserved-but-unwritten tail (worst-
        case admission reservations inflate it early in a request's
        life).  Also refreshes the per-tenant fragmentation gauge.
        Reads a snapshot of the slot list, so a concurrent decode
        iteration costs at most a stale row, never a crash."""
        page_len = int(self._engine.page_len)
        census: Dict[str, dict] = {}
        for s, st in enumerate(list(self._slots)):
            if st is None:
                continue
            t = str(st.req.tenant)
            row = census.setdefault(
                t, {"pages": 0, "written_tokens": 0, "requests": 0})
            row["pages"] += len(self._engine.cache.pages_of(s))
            row["written_tokens"] += int(st.pos)
            row["requests"] += 1
        for t, row in census.items():
            cap = row["pages"] * page_len
            row["frag"] = round(1.0 - row["written_tokens"] / cap,
                                4) if cap else 0.0
            _monitor.SERVING_KV_TENANT_FRAG.set(row["frag"], tenant=t)
        return census

    def _update_token_rate(self, now: float, n_gen: int,
                           window_s: float = 5.0) -> None:
        """Windowed generated-tokens/s into the gauge the heartbeat
        digest ships as ``tps`` (decode thread only — no lock)."""
        if n_gen:
            _monitor.SERVING_TOKENS_CTR.inc(n_gen)
        win = self._tok_win
        win.append((now, n_gen))
        while win and now - win[0][0] > window_s:
            win.popleft()
        # a lone sample after an idle gap carries no rate information:
        # floor its span at 1 s so the first token back doesn't publish
        # a phantom 1000 tok/s spike into the routing digest
        span = max(now - win[0][0], 1.0 if len(win) == 1 else 1e-3)
        _monitor.SERVING_TPS_GAUGE.set(
            round(sum(n for _, n in win) / span, 3))

    def _retire(self, s, st, now) -> None:
        self._kv_account(st.req.tenant,
                         -len(self._engine.cache.pages_of(s)))
        self._engine.release_slot(s)
        self._slots[s] = None
        out = np.asarray(st.generated, np.int32)
        done_t = time.perf_counter()
        e2e_ms = (done_t - st.req.t_submit) * 1e3
        tm = st.req.tm
        _emit_request_trace(st.req, (
            ("admit", tm.get("submit"), tm.get("enq")),
            ("queue_wait", tm.get("enq"), tm.get("slot")),
            ("decode", tm.get("slot"), now),
            ("materialize", now, done_t),
        ), e2e_ms, bucket="decode",
            extra={"decode": {"iters": st.iters,
                              "generated": len(st.generated)}})
        _monitor.SERVING_FREE_SLOTS_GAUGE.set(float(sum(
            1 for x in self._slots if x is None)))
        self._on_complete(st.req, out, e2e_ms)
        with self._cv:
            self._pending -= 1
            self._cv.notify_all()
