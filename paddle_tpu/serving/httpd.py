"""Live scrape surface: a stdlib-HTTP metrics server.

Until now the only fleet-facing view of a running process was the at-exit
file export (``FLAGS_telemetry_export_path``) — nothing a Prometheus
scraper, a router, or an autoscaler could poll live.  This module serves
three endpoints off the process-wide registry:

- ``GET /metrics``  — ``monitor.REGISTRY.to_prometheus()`` (text 0.0.4),
  the same bytes the file export writes, but live;
- ``GET /healthz``  — drain-aware liveness: 200 ``ok`` normally, 503
  ``draining`` once the owning server has stopped admitting (a load
  balancer takes the replica out of rotation BEFORE its drain finishes);
- ``GET /statusz``  — JSON operational snapshot (buckets + widths, slot
  occupancy, per-tenant queue depths, SLO burn state).

``FLAGS_metrics_port`` picks the port (0 = disabled; the server classes
start one automatically in ``serve_until_terminated``); port 0 passed
explicitly binds an ephemeral port (tests/smokes read ``.port``).

All request handling runs on daemon threads
(``http.server.ThreadingHTTPServer``); handlers only READ registry
snapshots and call the provider callbacks, so a slow scrape never blocks
the serving path.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from .. import monitor as _monitor

__all__ = ["MetricsHTTPServer"]

HTTP_REQ_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_metrics_http_requests_total",
    "scrape-endpoint requests served, by path and status",
    ("path", "status"))


class MetricsHTTPServer:
    """One process's scrape endpoint (``/metrics`` ``/healthz``
    ``/statusz``).

    ``health_fn() -> (ok, state)`` drives ``/healthz`` (state is the
    body, ok picks 200 vs 503); ``status_fn() -> dict`` feeds
    ``/statusz``.  Both default to an always-healthy, empty-status
    standalone exporter — a training rank can expose ``/metrics`` with
    no serving plane at all.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], Tuple[bool, str]]] = None,
                 status_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self._host = host
        self._requested_port = int(port)
        self._health_fn = health_fn or (lambda: (True, "ok"))
        self._status_fn = status_fn or (lambda: {})
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        #: the actually-bound port (ephemeral requests resolve at start)
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MetricsHTTPServer":
        if self._httpd is not None:
            return self
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # scrapes are high-frequency; stdlib's per-request stderr
            # line would drown real logs
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _reply(self, status: int, body: str,
                       ctype: str = "text/plain; charset=utf-8"):
                data = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802  (stdlib handler contract)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        status, body, ctype = (
                            200, _monitor.REGISTRY.to_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        ok, state = outer._health_fn()
                        status, body, ctype = (
                            200 if ok else 503, state + "\n",
                            "text/plain; charset=utf-8")
                    elif path == "/statusz":
                        status, body, ctype = (
                            200,
                            json.dumps(outer._status_fn(), indent=1,
                                       sort_keys=True, default=str),
                            "application/json")
                    else:
                        status, body, ctype = (
                            404, "not found\n",
                            "text/plain; charset=utf-8")
                except Exception as e:   # a provider bug must answer,
                    status, body, ctype = (  # not hang the scraper
                        500, f"internal error: {e!r}\n",
                        "text/plain; charset=utf-8")
                known = path if path in ("/metrics", "/healthz",
                                         "/statusz") else "other"
                # unknown paths share one label: a scanner probing
                # random URLs must not grow the registry unbounded
                HTTP_REQ_CTR.inc(1, path=known, status=str(status))
                try:
                    self._reply(status, body, ctype)
                except (BrokenPipeError, ConnectionError):
                    pass             # scraper went away mid-reply

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-metrics-http",
            daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        if self.port is None:
            raise RuntimeError("metrics HTTP server not started")
        # a wildcard bind is not a dialable address — hand back loopback
        host = "127.0.0.1" if self._host in ("", "0.0.0.0", "::") \
            else self._host
        return f"http://{host}:{self.port}"

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        # .url after stop must raise "not started", not hand out a dead
        # address an unrelated process may have re-bound by now
        self.port = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
