"""Closed-loop fleet autoscaler: the control loop that makes the serving
fleet self-driving (ROADMAP "fleet" arc, final leg).

Hosted next to the :class:`~paddle_tpu.serving.fleet.FleetRouter` (and,
when a gang coordinator is around, attached to its ``/statusz`` via
``attach_status_section``), the controller consumes the signals earlier
PRs built — per-replica ``srv_q``/``occ``/``slots``/``tps`` load digests
(PR 11), HBM headroom/OOM-risk (PR 15), and the router's fleet-level SLO
burn-rate plane — and drives three actuators every
``FLAGS_fleet_scale_eval_interval_s``:

**Spawn/retire (target-size policy).**  Sustained queue pressure plus
fast+slow SLO burn above threshold raises the target (bounded by
``FLAGS_fleet_max_replicas``) and spawns a replica through the launcher;
sustained idle lowers it (bounded by ``FLAGS_fleet_min_replicas``) and
retires one — ALWAYS through the PR-18 drain path (SIGTERM → the
replica's guard finishes its in-flight work → exit), never a kill.  A
replica the router declares dead is replaced to restore the target.
Hysteresis (``FLAGS_fleet_scale_{up,down}_ticks`` consecutive ticks) and
a post-decision cooldown (``FLAGS_fleet_scale_cooldown_s``) make the
loop flap-proof; every decision is exactly one count in
``paddle_tpu_fleet_scale_total{dir,reason}`` (spawn retries after a
failed launch never recount) and one trace instant.

**Shed-vs-scale arbitration.**  On SLO breach the controller chooses
between admission shedding (cheap, immediate — requires
``FLAGS_serving_slo_shed``) and scale-up (slow, bounded): shedding
engages only after ``FLAGS_fleet_shed_after_ticks`` breached ticks AND
only while a spawn is in flight (or has failed and is backing off) or
the fleet is already pinned at max — and releases the moment the new
replica reports fresh or the breach clears.

**Degradation ladder.**  A replica reporting HBM headroom under
``FLAGS_fleet_oom_headroom_frac`` (the PR-15 OOM-risk signal riding the
load digest as ``hbm``/``hdrm``) first gets a per-replica ``control``
op that halves its bucket widths — a local, reversible-by-respawn action
taken before any global one.  A replica still at risk
``FLAGS_fleet_shrink_grace_ticks`` ticks after its shrink is drained and
respawned fresh.

Failure containment: an injected/real fault in the decide path skips
that tick whole (half a decision must not actuate); a spawn failure
backs off ``FLAGS_fleet_spawn_backoff_s`` and keeps shedding engaged
while the breach lasts; nothing propagates out of the loop thread.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import monitor as _monitor
from .. import resilience as _resil

__all__ = ["AutoscalerPolicy", "Decision", "FleetAutoscaler"]

log = logging.getLogger("paddle_tpu")

#: replica states that count toward the fleet's live size (draining and
#: dead replicas are already out of placement)
_LIVE_STATES = ("up", "stale")


def _instant(name: str, args: Dict[str, Any]) -> None:
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant(name, "autoscaler", args)


class Decision:
    """One tick's verdict from :class:`AutoscalerPolicy` — pure data the
    :class:`FleetAutoscaler` actuates."""

    __slots__ = ("spawn", "spawn_reason", "retire", "shed", "shrink",
                 "respawn", "count")

    def __init__(self, spawn: bool = False, spawn_reason: str = "",
                 retire: Optional[str] = None,
                 shed: Optional[bool] = None,
                 shrink: Optional[List[str]] = None,
                 respawn: Optional[List[str]] = None,
                 count: Optional[List[tuple]] = None):
        self.spawn = bool(spawn)          # initiate one replica spawn
        self.spawn_reason = spawn_reason  # trace label for the spawn
        self.retire = retire              # addr to drain-and-retire
        self.shed = shed                  # new shed state (None = keep)
        self.shrink = list(shrink or ())  # addrs to send shrink_width
        self.respawn = list(respawn or ())  # addrs to drain + respawn
        #: (dir, reason) pairs to count in fleet_scale_total — exactly
        #: the decisions made THIS tick, never retries of older ones
        self.count = list(count or ())

    def __repr__(self):
        return (f"Decision(spawn={self.spawn}/{self.spawn_reason!r}, "
                f"retire={self.retire!r}, shed={self.shed}, "
                f"shrink={self.shrink}, respawn={self.respawn}, "
                f"count={self.count})")


class AutoscalerPolicy:
    """The decision table, isolated from threads/sockets/clocks so the
    unit tests drive it tick by tick with synthetic signals.

    ``decide(sig)`` consumes one signal snapshot::

        {"replicas": {addr: {"state": str, "srv_q": float,
                             "hdrm_frac": float|None, "fresh": bool}},
         "breached": bool,          # fleet SLO burn, both windows
         "qps": float,              # fleet completion rate (req/s)
         "spawn_inflight": bool,    # spawn worker alive OR backing off
         "retire_inflight": bool}

    and returns a :class:`Decision`.  NOT thread-safe by itself: the
    FleetAutoscaler serializes ``decide()`` and ``status`` reads under
    its own lock.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 queue_high: float = 4.0, idle_qps: float = 0.5,
                 up_ticks: int = 2, down_ticks: int = 5,
                 cooldown_ticks: int = 15, shed_after_ticks: int = 2,
                 oom_frac: float = 0.10, shrink_grace_ticks: int = 3,
                 shed_enabled: bool = False,
                 initial_target: Optional[int] = None):
        self.min = max(1, int(min_replicas))
        self.max = max(self.min, int(max_replicas))
        self.queue_high = float(queue_high)
        self.idle_qps = float(idle_qps)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.shed_after_ticks = max(1, int(shed_after_ticks))
        self.oom_frac = float(oom_frac)
        self.shrink_grace_ticks = max(1, int(shrink_grace_ticks))
        self.shed_enabled = bool(shed_enabled)
        tgt = self.min if initial_target is None else int(initial_target)
        self.target = min(self.max, max(self.min, tgt))
        self.shed_on = False
        self.last: Dict[str, Any] = {"action": "none", "reason": ""}
        self._up = 0                  # consecutive scale-up-worthy ticks
        self._down = 0                # consecutive idle ticks
        self._breach_ticks = 0        # consecutive breached ticks
        self._cooldown = 0            # ticks of scale freeze remaining
        self._shrunk: set = set()     # replicas already sent a shrink
        self._risk: Dict[str, int] = {}   # post-shrink at-risk ticks
        self._dead_seen: set = set()  # dead replicas already counted
        self._surplus_counted = False  # current surplus episode counted

    @property
    def cooldown(self) -> int:
        return self._cooldown

    @classmethod
    def from_flags(cls, initial_target: Optional[int] = None,
                   interval_s: Optional[float] = None
                   ) -> "AutoscalerPolicy":
        from ..flags import get_flags
        fl = get_flags([
            "FLAGS_fleet_min_replicas", "FLAGS_fleet_max_replicas",
            "FLAGS_fleet_scale_eval_interval_s",
            "FLAGS_fleet_scale_up_ticks", "FLAGS_fleet_scale_down_ticks",
            "FLAGS_fleet_scale_cooldown_s", "FLAGS_fleet_queue_high",
            "FLAGS_fleet_idle_qps", "FLAGS_fleet_shed_after_ticks",
            "FLAGS_fleet_oom_headroom_frac",
            "FLAGS_fleet_shrink_grace_ticks", "FLAGS_serving_slo_shed"])
        dt = float(interval_s if interval_s is not None
                   else fl["FLAGS_fleet_scale_eval_interval_s"])
        # the cooldown flag is seconds; the policy thinks in ticks
        cooldown_ticks = int(round(
            float(fl["FLAGS_fleet_scale_cooldown_s"]) / max(dt, 1e-9)))
        return cls(
            min_replicas=int(fl["FLAGS_fleet_min_replicas"]),
            max_replicas=int(fl["FLAGS_fleet_max_replicas"]),
            queue_high=float(fl["FLAGS_fleet_queue_high"]),
            idle_qps=float(fl["FLAGS_fleet_idle_qps"]),
            up_ticks=int(fl["FLAGS_fleet_scale_up_ticks"]),
            down_ticks=int(fl["FLAGS_fleet_scale_down_ticks"]),
            cooldown_ticks=cooldown_ticks,
            shed_after_ticks=int(fl["FLAGS_fleet_shed_after_ticks"]),
            oom_frac=float(fl["FLAGS_fleet_oom_headroom_frac"]),
            shrink_grace_ticks=int(fl["FLAGS_fleet_shrink_grace_ticks"]),
            shed_enabled=bool(fl["FLAGS_serving_slo_shed"]),
            initial_target=initial_target)

    # -- the decision table --------------------------------------------------
    def decide(self, sig: Dict[str, Any]) -> Decision:
        reps: Dict[str, dict] = sig.get("replicas") or {}
        live = [a for a, r in reps.items()
                if r.get("state") in _LIVE_STATES]
        nlive = len(live)
        count: List[tuple] = []

        # forget ladder/death state for replicas no longer in the table
        known = set(reps)
        self._shrunk &= known
        for a in list(self._risk):
            if a not in known:
                del self._risk[a]
        self._dead_seen &= known

        if self._cooldown > 0:
            self._cooldown -= 1

        # 1) degradation ladder — per-replica, LOCAL action first
        shrink: List[str] = []
        respawn: List[str] = []
        for a in live:
            frac = reps[a].get("hdrm_frac")
            at_risk = frac is not None and frac < self.oom_frac
            if not at_risk:
                self._risk.pop(a, None)
                continue
            if a not in self._shrunk:
                self._shrunk.add(a)
                self._risk[a] = 0
                shrink.append(a)
            else:
                n = self._risk.get(a, 0) + 1
                self._risk[a] = n
                if n >= self.shrink_grace_ticks:
                    # the shrink did not clear the risk: last rung —
                    # drain this replica and respawn it fresh
                    respawn.append(a)
                    self._shrunk.discard(a)
                    self._risk.pop(a, None)
                    count.append(("up", "oom"))

        qs = [float(reps[a].get("srv_q", 0.0)) for a in live]
        mean_q = (sum(qs) / len(qs)) if qs else 0.0
        breached = bool(sig.get("breached"))

        # 2) scale-up hysteresis: burn + queue pressure, sustained
        if breached and mean_q >= self.queue_high:
            self._up += 1
        else:
            self._up = 0
        bumped = False
        if self._up >= self.up_ticks and self._cooldown == 0 \
                and self.target < self.max:
            self.target += 1
            self._cooldown = self.cooldown_ticks
            self._up = 0
            bumped = True
            count.append(("up", "burn_queue"))

        # 3) scale-down hysteresis: no breach, empty queues, idle rate
        per_rep_qps = float(sig.get("qps", 0.0)) / max(nlive, 1)
        idle = (not breached) and mean_q <= 1e-9 \
            and per_rep_qps < self.idle_qps
        if idle:
            self._down += 1
        else:
            self._down = 0
        lowered = False
        if self._down >= self.down_ticks and self._cooldown == 0 \
                and self.target > self.min:
            self.target -= 1
            self._cooldown = self.cooldown_ticks
            self._down = 0
            lowered = True
            count.append(("down", "idle"))

        # 4) death repair: every NEWLY dead replica is one counted
        # decision; the reconcile below spawns the replacement.  The
        # dead entry stays in the router table (the prober keeps
        # knocking — a replica that was merely partitioned rejoins, and
        # the resulting surplus retires gracefully below).
        for a, r in reps.items():
            if r.get("state") == "dead" and a not in self._dead_seen:
                self._dead_seen.add(a)
                if nlive < self.target:
                    count.append(("up", "death"))
            elif r.get("state") in _LIVE_STATES:
                self._dead_seen.discard(a)

        # 5) reconcile live size against the target
        spawn, spawn_reason = False, ""
        retire: Optional[str] = None
        if nlive <= self.target:
            self._surplus_counted = False
        if nlive < self.target and not sig.get("spawn_inflight") \
                and not respawn:
            spawn = True
            spawn_reason = "burn_queue" if bumped else \
                ("death" if self._dead_seen else "repair")
        elif nlive > self.target and not sig.get("retire_inflight") \
                and not respawn:
            # retire the least-loaded fresh replica (prefer fresh: its
            # in-flight picture is trustworthy).  A surplus without a
            # target change (a revived dead replica) counts once per
            # episode — the decision, not each tick the drain takes
            pool = sorted(
                live, key=lambda a: (not reps[a].get("fresh"),
                                     float(reps[a].get("srv_q", 0.0))))
            if pool:
                retire = pool[0]
                if not lowered and not self._surplus_counted:
                    count.append(("down", "surplus"))
                self._surplus_counted = True

        # 6) shed-vs-scale arbitration
        if breached:
            self._breach_ticks += 1
        else:
            self._breach_ticks = 0
        at_max = self.target >= self.max
        want_shed = (self.shed_enabled and breached
                     and self._breach_ticks >= self.shed_after_ticks
                     and (bool(sig.get("spawn_inflight")) or spawn
                          or at_max))
        shed = None if want_shed == self.shed_on else want_shed
        self.shed_on = want_shed

        if bumped:
            self.last = {"action": "scale_up", "reason": "burn_queue"}
        elif lowered:
            self.last = {"action": "scale_down", "reason": "idle"}
        elif respawn:
            self.last = {"action": "respawn", "reason": "oom"}
        elif spawn:
            self.last = {"action": "spawn", "reason": spawn_reason}
        elif retire:
            self.last = {"action": "retire", "reason": "surplus"}
        elif shrink:
            self.last = {"action": "shrink", "reason": "oom_headroom"}
        return Decision(spawn=spawn, spawn_reason=spawn_reason,
                        retire=retire, shed=shed, shrink=shrink,
                        respawn=respawn, count=count)


class FleetAutoscaler:
    """The loop host: reads signals off a
    :class:`~paddle_tpu.serving.fleet.FleetRouter`, runs the
    :class:`AutoscalerPolicy`, and actuates through two injected
    callables so the same controller drives subprocess replicas
    (``tools/fleet_smoke.py``), launcher-spawned ones
    (:class:`~paddle_tpu.distributed.launch.ReplicaLauncher`), or test
    stubs:

    * ``spawn_fn() -> addr`` — start one replica, block until it is
      ready, return its ``host:port`` (runs on a worker thread — the
      control loop keeps ticking, which is what lets shedding engage
      while the spawn warms up);
    * ``retire_fn(addr)`` — drain-then-stop the replica at ``addr``
      (SIGTERM + wait; NEVER a kill), block until it exited.

    ``tick()`` is public and takes an optional ``now`` so tests drive
    the loop deterministically without the thread.
    """

    def __init__(self, router, spawn_fn: Callable[[], str],
                 retire_fn: Callable[[str], Any],
                 policy: Optional[AutoscalerPolicy] = None,
                 interval_s: Optional[float] = None,
                 clock=time.monotonic):
        from ..flags import get_flags
        fl = get_flags(["FLAGS_fleet_scale_eval_interval_s",
                        "FLAGS_fleet_spawn_backoff_s"])
        self.router = router
        self._spawn_fn = spawn_fn
        self._retire_fn = retire_fn
        self.interval_s = float(
            interval_s if interval_s is not None
            else fl["FLAGS_fleet_scale_eval_interval_s"])
        self._backoff_s = float(fl["FLAGS_fleet_spawn_backoff_s"])
        self._clock = clock
        live0 = sum(1 for r in router.replica_view().values()
                    if r.get("state") in _LIVE_STATES)
        self.policy = policy or AutoscalerPolicy.from_flags(
            initial_target=max(live0, 1), interval_s=self.interval_s)
        self._mu = threading.Lock()
        # policy state is mutated only through decide()/status() calls
        # made under _mu — the policy object itself stays lock-free
        self._spawn_thread: Optional[threading.Thread] = None  # guarded-by: _mu
        self._retire_thread: Optional[threading.Thread] = None  # guarded-by: _mu
        self._backoff_until = 0.0     # guarded-by: _mu
        self._spawn_failures = 0      # guarded-by: _mu
        self._last_size = live0       # guarded-by: _mu
        self._ticks = 0               # guarded-by: _mu
        self._qps_mark: Optional[tuple] = None  # guarded-by: _mu
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="pt-autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._mu:
            workers = [self._spawn_thread, self._retire_thread]
        for t in workers:
            if t is not None:
                t.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the controller must outlive any single bad tick —
                # a dead autoscaler is a silently static fleet
                log.warning("autoscaler tick failed: %r", e)
                _instant("autoscaler.tick_error", {"error": repr(e)[:200]})

    # -- one control tick ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Run one decide→actuate cycle; returns the status snapshot.
        An injected ``autoscaler.decide`` fault skips the tick whole —
        half a decision must never actuate."""
        now = self._clock() if now is None else now
        try:
            _resil.maybe_inject("autoscaler.decide")
        except _resil.InjectedFault as e:
            _instant("autoscaler.tick_skipped", {"error": repr(e)[:200]})
            return self.status()

        breached = False
        if getattr(self.router, "slo", None) is not None:
            try:
                st = self.router.slo.evaluate()
                breached = any(v.get("breached") for v in st.values())
            except Exception as e:   # the SLO plane must not kill ticks
                _instant("autoscaler.slo_error", {"error": repr(e)[:200]})

        view = self.router.replica_view()
        snap = self.router.snapshot()
        reps: Dict[str, dict] = {}
        for a, r in view.items():
            load = r.get("load") or {}
            hdrm_frac = None
            hbm, hd = load.get("hbm"), load.get("hdrm")
            if hbm is not None and hd is not None and (hbm + hd) > 0:
                hdrm_frac = float(hd) / (float(hbm) + float(hd))
            reps[a] = {"state": r.get("state"),
                       "fresh": bool(r.get("fresh")),
                       "srv_q": float(load.get("srv_q", 0.0)),
                       "hdrm_frac": hdrm_frac}
        completed = int(snap.get("completed", 0))
        with self._mu:
            spawning = (self._spawn_thread is not None
                        and self._spawn_thread.is_alive())
            retiring = (self._retire_thread is not None
                        and self._retire_thread.is_alive())
            # a failed spawn's backoff window COUNTS as in-flight: it
            # gates the retry and keeps shedding engaged (the re-shed
            # contract for injected spawn failures)
            spawn_inflight = spawning or now < self._backoff_until
            mark, self._qps_mark = self._qps_mark, (now, completed)
        qps = 0.0
        if mark is not None and now > mark[0]:
            qps = max(0.0, (completed - mark[1]) / (now - mark[0]))

        sig = {"replicas": reps, "breached": breached, "qps": qps,
               "spawn_inflight": spawn_inflight,
               "retire_inflight": retiring}
        nlive = sum(1 for r in reps.values()
                    if r.get("state") in _LIVE_STATES)
        with self._mu:
            decision = self.policy.decide(sig)
            self._last_size = nlive
            self._ticks += 1
            target, shed_on = self.policy.target, self.policy.shed_on

        for dir_, reason in decision.count:
            _monitor.FLEET_SCALE_CTR.inc(1, dir=dir_, reason=reason)
            _instant("autoscaler.scale",
                     {"dir": dir_, "reason": reason, "target": target,
                      "size": nlive})
        if decision.shed is not None:
            self.router.set_shedding(decision.shed)
            _instant("autoscaler.shed",
                     {"on": decision.shed, "target": target,
                      "size": nlive})
        for addr in decision.shrink:
            self._shrink_replica(addr)
        for addr in decision.respawn:
            self._start_retire(addr, respawn=True)
        if decision.retire is not None and not decision.respawn:
            self._start_retire(decision.retire, respawn=False)
        if decision.spawn and not decision.respawn:
            self._start_spawn(decision.spawn_reason)

        _monitor.FLEET_TARGET_GAUGE.set(float(target))
        _monitor.FLEET_SIZE_GAUGE.set(float(nlive))
        _monitor.FLEET_SHED_GAUGE.set(1.0 if shed_on else 0.0)
        return self.status()

    # -- actuators -----------------------------------------------------------
    def _shrink_replica(self, addr: str) -> None:
        """Ladder rung 1: the per-replica bucket-width shrink control
        op.  An ``unsupported`` reply (no bucket plan to shrink) is
        fine: the policy's post-shrink grace counter keeps running, so
        a still-at-risk replica escalates to drain-and-respawn."""
        try:
            resp = self.router.control(addr, "shrink_width")
        except Exception as e:
            _instant("autoscaler.shrink_failed",
                     {"replica": addr, "error": repr(e)[:200]})
            return
        if resp.get("ok"):
            _monitor.FLEET_SHRINK_CTR.inc(1)
            _instant("autoscaler.shrink",
                     {"replica": addr, "widths": resp.get("widths")})
        else:
            _instant("autoscaler.shrink_refused",
                     {"replica": addr, "error": resp.get("error")})

    def _start_spawn(self, reason: str) -> None:
        with self._mu:
            if self._spawn_thread is not None \
                    and self._spawn_thread.is_alive():
                return
            t = threading.Thread(target=self._spawn_body, args=(reason,),
                                 daemon=True, name="pt-autoscaler-spawn")
            self._spawn_thread = t
        t.start()

    def _spawn_body(self, reason: str) -> None:
        try:
            _resil.maybe_inject("autoscaler.spawn")
            addr = self._spawn_fn()
            self.router.add_replica(str(addr))
            _instant("autoscaler.spawned",
                     {"replica": str(addr), "reason": reason})
        except Exception as e:
            # back off — the next ticks see spawn_inflight (backoff
            # window) so shedding stays engaged while the breach lasts,
            # and the retry waits out the backoff.  The controller loop
            # itself never sees this exception.
            with self._mu:
                self._spawn_failures += 1
                self._backoff_until = self._clock() + self._backoff_s
            _instant("autoscaler.spawn_failed",
                     {"reason": reason, "error": repr(e)[:200],
                      "backoff_s": self._backoff_s})

    def _start_retire(self, addr: str, respawn: bool) -> None:
        with self._mu:
            if self._retire_thread is not None \
                    and self._retire_thread.is_alive():
                return
            t = threading.Thread(target=self._retire_body,
                                 args=(addr, respawn), daemon=True,
                                 name="pt-autoscaler-retire")
            self._retire_thread = t
        # hold the replica out of placement NOW — the drain refusals
        # would get there too, but only after a client bounced off it
        self.router._mark_draining(addr)
        t.start()

    def _retire_body(self, addr: str, respawn: bool) -> None:
        try:
            _resil.maybe_inject("autoscaler.retire")
        except _resil.InjectedFault as e:
            # the replica was never SIGTERM'd: its next reply reports
            # draining=False and the router restores it to "up" —
            # the aborted retire self-heals
            _instant("autoscaler.retire_skipped",
                     {"replica": addr, "error": repr(e)[:200]})
            return
        try:
            self._retire_fn(addr)
            self.router.remove_replica(addr)
            _instant("autoscaler.retired",
                     {"replica": addr, "respawn": respawn})
        except Exception as e:
            _instant("autoscaler.retire_failed",
                     {"replica": addr, "error": repr(e)[:200]})
            return
        if respawn:
            # ladder's last rung, second half: replace the drained
            # replica with a fresh one (fresh process = fresh HBM)
            self._spawn_body("oom")

    # -- status --------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """The controller's operational snapshot — what gangtop's
        TGT/SIZE footer and the coordinator's ``/statusz`` autoscaler
        section render."""
        with self._mu:
            pol = self.policy
            spawning = (self._spawn_thread is not None
                        and self._spawn_thread.is_alive())
            return {"target": pol.target, "min": pol.min,
                    "max": pol.max, "size": self._last_size,
                    "shedding": pol.shed_on,
                    "cooldown_ticks": pol.cooldown,
                    "spawn_inflight": spawning,
                    "spawn_failures": self._spawn_failures,
                    "ticks": self._ticks, "last": dict(pol.last)}

    def attach_to(self, coordinator) -> None:
        """Ride the gang coordinator's status plane: the controller's
        snapshot appears as the ``autoscaler`` section of
        ``status_snapshot()`` / ``/statusz`` / gangtop."""
        coordinator.attach_status_section("autoscaler", self.status)
