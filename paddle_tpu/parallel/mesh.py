"""Device-mesh management — the TPU-native replacement for the reference's
NCCL context plumbing (``platform/nccl_helper.h:75-300``,
``platform/collective_helper.h:50``).

Where the reference builds NCCL rings per place (flat + hierarchical
inter/intra-node), here a single ``jax.sharding.Mesh`` carries every
parallelism axis and XLA lays collectives onto ICI/DCN:

- ``dp``  — data parallel (≈ AllReduceSSAGraphBuilder / c_allreduce ring)
- ``mp``  — tensor/model parallel (capability the reference lacks; SURVEY §2.5)
- ``sp``  — sequence/context parallel (ring attention axis)
- ``pp``  — pipeline stages (≈ PipelineTrainer sections)
- ``ep``  — expert parallel (MoE)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "mp", "sp", "pp", "ep")

_current_mesh: Optional[Mesh] = None


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}; sizes must multiply to #devices.

    Axis order follows AXES so dp is outermost (DCN-friendly) and mp/sp
    innermost (ICI-friendly) — the hierarchical-allreduce layout the
    reference approximates with inter/intra-node NCCL rings
    (nccl_helper.h:246 InitHierarchicalCtxs).
    """
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXES if a in axes] + \
        [a for a in axes if a not in AXES]
    sizes = [axes[a] for a in names]
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {axes} needs {int(np.prod(sizes))} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({"dp": len(devs)}, devs)


def set_mesh(mesh: Optional[Mesh]):
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


def sharding_for(mesh: Mesh, spec) -> NamedSharding:
    """spec: None (replicated) or a tuple of axis-names/None per dim, with
    axes absent from the mesh silently dropped (so a tp-annotated model runs
    unchanged on a dp-only mesh)."""
    if spec is None:
        return NamedSharding(mesh, P())
    clean = tuple(
        (a if (a is not None and _axis_in(mesh, a)) else None)
        for a in spec)
    return NamedSharding(mesh, P(*clean))


def _axis_in(mesh: Mesh, axis) -> bool:
    if isinstance(axis, (tuple, list)):
        return all(a in mesh.axis_names for a in axis)
    return axis in mesh.axis_names


def make_topology_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Hardware-topology-aware Mesh from {axis_name: size} — the GSPMD
    partitioner's mesh constructor (SNIPPETS.md [2]:
    ``mesh_utils.create_device_mesh`` / ``create_hybrid_device_mesh``).

    Unlike :func:`make_mesh`'s row-major reshape, ``mesh_utils`` orders
    devices so the innermost (mp/sp) axes land on physically adjacent
    chips — ICI rings for the model-parallel collectives, DCN only
    across the outermost (dp) axis.  Multi-host meshes go through the
    hybrid constructor (one slow axis per granule, fast axes inside);
    anything mesh_utils cannot map (CPU fan-outs, odd shapes) falls
    back to :func:`make_mesh`, which is always valid, just not
    bandwidth-optimal."""
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXES if a in axes] + \
        [a for a in axes if a not in AXES]
    sizes = [int(axes[a]) for a in names]
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh {axes} needs {int(np.prod(sizes))} devices, "
            f"have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        n_hosts = len({getattr(d, "process_index", 0) for d in devices})
        if n_hosts > 1 and len(sizes) > 1:
            per_host = len(devices) // n_hosts
            # split each axis between the DCN (host) and ICI (chip)
            # levels, outermost axes absorbing the host factor first
            dcn, ici, hosts_left = [], [], n_hosts
            for s in sizes:
                g = np.gcd(s, hosts_left)
                dcn.append(int(g))
                ici.append(s // int(g))
                hosts_left //= int(g)
            if hosts_left == 1 and int(np.prod(ici)) == per_host:
                arr = mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=devices)
                return Mesh(arr, axis_names=tuple(names))
        arr = mesh_utils.create_device_mesh(sizes, devices=devices)
        return Mesh(arr, axis_names=tuple(names))
    except Exception:
        return make_mesh(axes, devices)


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    """{axis_name: size} of a Mesh — the partitioner's planner input."""
    return {str(a): int(s)
            for a, s in zip(mesh.axis_names, mesh.devices.shape)}


def make_hierarchical_mesh(inter: int, intra: int, devices=None) -> Mesh:
    """2-level data-parallel mesh (ref SURVEY §2.5 hierarchical allreduce:
    ``NCCLCommunicator::InitHierarchicalCtxs`` inter/intra-node rings).

    On TPU the two levels are DCN (between slices/hosts) and ICI (inside a
    slice): build a ``("dcn", "ici")`` mesh and shard the batch over BOTH
    axes; XLA lowers the gradient psum into an ICI-local reduce followed by
    a DCN exchange — the exact hierarchical-allreduce structure the
    reference hand-builds, chosen automatically from the mesh topology.
    ``hierarchical_allreduce`` exposes the explicit two-stage form for
    shard_map code."""
    return make_mesh({"dcn": inter, "ici": intra}, devices)


def hierarchical_allreduce(x, inter_axis: str = "dcn",
                           intra_axis: str = "ici"):
    """Explicit two-stage allreduce over a hierarchical mesh (inside
    shard_map): reduce over the fast intra axis first, then the slow inter
    axis — same result as one psum over both, with the collective order
    pinned (ref nccl_helper.h:246 hierarchical inter/exter comms)."""
    from jax import lax
    return lax.psum(lax.psum(x, intra_axis), inter_axis)
