"""Device-mesh management — the TPU-native replacement for the reference's
NCCL context plumbing (``platform/nccl_helper.h:75-300``,
``platform/collective_helper.h:50``).

Where the reference builds NCCL rings per place (flat + hierarchical
inter/intra-node), here a single ``jax.sharding.Mesh`` carries every
parallelism axis and XLA lays collectives onto ICI/DCN:

- ``dp``  — data parallel (≈ AllReduceSSAGraphBuilder / c_allreduce ring)
- ``mp``  — tensor/model parallel (capability the reference lacks; SURVEY §2.5)
- ``sp``  — sequence/context parallel (ring attention axis)
- ``pp``  — pipeline stages (≈ PipelineTrainer sections)
- ``ep``  — expert parallel (MoE)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "mp", "sp", "pp", "ep")

_current_mesh: Optional[Mesh] = None


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}; sizes must multiply to #devices.

    Axis order follows AXES so dp is outermost (DCN-friendly) and mp/sp
    innermost (ICI-friendly) — the hierarchical-allreduce layout the
    reference approximates with inter/intra-node NCCL rings
    (nccl_helper.h:246 InitHierarchicalCtxs).
    """
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXES if a in axes] + \
        [a for a in axes if a not in AXES]
    sizes = [axes[a] for a in names]
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {axes} needs {int(np.prod(sizes))} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({"dp": len(devs)}, devs)


def set_mesh(mesh: Optional[Mesh]):
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


def sharding_for(mesh: Mesh, spec) -> NamedSharding:
    """spec: None (replicated) or a tuple of axis-names/None per dim, with
    axes absent from the mesh silently dropped (so a tp-annotated model runs
    unchanged on a dp-only mesh)."""
    if spec is None:
        return NamedSharding(mesh, P())
    clean = tuple(
        (a if (a is not None and _axis_in(mesh, a)) else None)
        for a in spec)
    return NamedSharding(mesh, P(*clean))


def _axis_in(mesh: Mesh, axis) -> bool:
    if isinstance(axis, (tuple, list)):
        return all(a in mesh.axis_names for a in axis)
    return axis in mesh.axis_names
