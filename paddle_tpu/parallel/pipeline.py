"""Pipeline parallelism: program sections over devices, GPipe schedule.

Reference: ``PipelineOptimizer`` (``python/paddle/fluid/optimizer.py:2687``)
splits a program at ``cut_list`` vars into sections placed on heterogeneous
devices, executed by ``PipelineTrainer``/``SectionWorker``
(``framework/trainer.h:110``, ``framework/device_worker.h:262``) with
scope queues between stages.

TPU-native redesign:

- the *split* stays program-level (ops between cut vars form a Section, a
  standalone sub-Program), but
- the *runtime* is functional: each section lowers to one jitted XLA
  computation pinned to its pipeline device; activations move stage→stage
  as committed device arrays (ICI transfers), and JAX's async dispatch
  overlaps stage s of microbatch m with stage s+1 of microbatch m-1 — the
  role the reference's scope queues + section worker threads play.
- backward is recompute-based (each section's vjp re-runs its forward
  inside one jitted computation) — the rematerialization trade the
  reference approximates by dropping per-microbatch scopes.
- optimizer apply reuses the *same* ``Optimizer._append_optimize_op``
  kernels through the eager shim, so all optimizers work per-stage
  unchanged (the reference shares optimize ops between modes the same
  way, ``imperative/prepared_operator.h``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..framework.core import Operator, Program, Variable


class Section:
    """One pipeline stage: a sub-program plus its boundary signature.

    ≈ the reference's per-section program (SectionWorkerParameter,
    ``framework/trainer_desc.proto:66-86``).
    """

    def __init__(self, idx: int, program: Program, in_names: List[str],
                 feed_names: List[str], out_names: List[str],
                 param_names: List[str]):
        self.idx = idx
        self.program = program
        self.in_names = in_names        # activations from the previous stage
        self.feed_names = feed_names    # raw feeds this stage consumes
        self.out_names = out_names      # activations for later stages
        self.param_names = param_names

    def __repr__(self):
        return (f"Section({self.idx}, ops={len(self.program.global_block().ops)}, "
                f"in={self.in_names}, feed={self.feed_names}, "
                f"out={self.out_names})")


def _copy_section_program(src_block, ops: Sequence[Operator]) -> Program:
    """Clone a slice of ops (with the vars they touch) into a fresh Program."""
    prog = Program()
    blk = prog.global_block()
    for op in ops:
        for name in op.input_arg_names() + op.output_arg_names():
            if blk.has_var(name):
                continue
            if src_block.has_var(name):
                v = src_block.var(name)
                nv = Variable(blk, name, shape=v.shape, dtype=v.dtype,
                              persistable=v.persistable,
                              is_parameter=v.is_parameter,
                              trainable=getattr(v, "trainable", False))
                nv.stop_gradient = getattr(v, "stop_gradient", False)
                blk.vars[name] = nv
            else:
                blk.create_var(name=name)
    for op in ops:
        # raw copy, no re-inference: var metadata came from the source block
        new = Operator(blk, op.type, dict(op.inputs), dict(op.outputs),
                       dict(op.attrs))
        blk.ops.append(new)
    return prog


def split_program(program: Program, cut_vars: Sequence,
                  loss_name: str) -> List[Section]:
    """Split at cut vars: section s = ops after the producer of cut s-1 up
    to (and including) the producer of cut s (ref PipelineOptimizer's
    cut_list semantics, optimizer.py:2687)."""
    block = program.global_block()
    cut_names = [c.name if isinstance(c, Variable) else c for c in cut_vars]
    ops = list(block.ops)

    producer_idx = {}
    for i, op in enumerate(ops):
        for name in op.output_arg_names():
            producer_idx[name] = i

    bounds = []
    for c in cut_names:
        if c not in producer_idx:
            raise ValueError(f"cut var {c!r} is not produced by any op")
        bounds.append(producer_idx[c])
    if bounds != sorted(bounds):
        raise ValueError("cut_list must be topologically ordered")
    bounds = bounds + [len(ops) - 1]

    produced_by_stage: Dict[str, int] = {}
    feed_candidates = set()
    for op in ops:
        for name in op.input_arg_names():
            if name not in producer_idx and not block.var(name).persistable:
                feed_candidates.add(name)

    sections = []
    start = 0
    slices = []
    for s, end in enumerate(bounds):
        sec_ops = ops[start:end + 1]
        slices.append(sec_ops)
        for op in sec_ops:
            for name in op.output_arg_names():
                produced_by_stage[name] = s
        start = end + 1

    # consumers: which stages read each var
    consumed_by: Dict[str, set] = {}
    for s, sec_ops in enumerate(slices):
        for op in sec_ops:
            for name in op.input_arg_names():
                consumed_by.setdefault(name, set()).add(s)

    for s, sec_ops in enumerate(slices):
        internal = set()
        params, ins, feeds = [], [], []
        for op in sec_ops:
            for name in op.input_arg_names():
                if name in internal:
                    continue
                v = block.var(name)
                if v.persistable:
                    if name not in params:
                        params.append(name)
                elif name in feed_candidates:
                    if name not in feeds:
                        feeds.append(name)
                elif produced_by_stage.get(name, s) != s:
                    if name not in ins:
                        ins.append(name)
            for name in op.output_arg_names():
                internal.add(name)
        outs = []
        for op in sec_ops:
            for name in op.output_arg_names():
                later = any(t > s for t in consumed_by.get(name, ()))
                if (later or name == loss_name) and name not in outs:
                    outs.append(name)
        sections.append(Section(s, _copy_section_program(block, sec_ops),
                                ins, feeds, outs, params))
    return sections


class PipelineEngine:
    """GPipe runtime over sections (≈ PipelineTrainer + SectionWorkers).

    fwd: every microbatch flows through the jitted section functions, each
    pinned to its device; boundary activations are stashed per microbatch.
    bwd: reverse order, each section's vjp recomputes its forward; param
    grads accumulate (mean over microbatches).  apply: inner optimizer's
    eager kernels update each stage's params on its own device.
    """

    def __init__(self, sections: List[Section], loss_name: str,
                 optimizer, num_microbatches: int,
                 devices: Optional[List] = None, scope=None):
        from ..framework.function import program_as_function
        from ..framework.scope import global_scope
        from ..dygraph.tracer import VarBase

        self.sections = sections
        self.loss_name = loss_name
        self.optimizer = optimizer
        self.num_microbatches = num_microbatches
        all_devs = jax.devices()
        if devices is None:
            devices = [all_devs[s % len(all_devs)]
                       for s in range(len(sections))]
        # a stage placement is one device OR a list of devices — a list
        # becomes a per-stage dp submesh (pp × dp composition: the ref
        # PipelineTrainer pins one worker per stage; here a stage can
        # itself be data-parallel over its slice of the pod)
        self.devices = devices
        self._stage_shardings = []
        for dv in devices:
            if isinstance(dv, (list, tuple)):
                if not dv:
                    raise ValueError(
                        "a pipeline stage got an EMPTY device list — "
                        f"{len(all_devs)} device(s) visible; check the "
                        "per-stage device partition")
                from jax.sharding import (Mesh, NamedSharding,
                                          PartitionSpec as P)
                mesh = Mesh(np.array(dv), ("dp",))
                self._stage_shardings.append(
                    (NamedSharding(mesh, P("dp")),      # batch-sharded
                     NamedSharding(mesh, P())))         # replicated
            else:
                self._stage_shardings.append(None)

        scope = scope or global_scope()
        self._vbs: List[Dict[str, VarBase]] = []
        self._fwd, self._bwd = [], []
        for s, sec in enumerate(sections):
            vbs = {}
            for name in sec.param_names:
                val = scope.find_var(name)
                if val is None:
                    raise RuntimeError(
                        f"parameter {name!r} not initialized — run the "
                        f"startup program first")
                vb = VarBase(self._put(val, s, replicate=True), name=name,
                             persistable=True, trainable=True)
                vbs[name] = vb
            self._vbs.append(vbs)

            fn = program_as_function(sec.program,
                                     sec.in_names + sec.feed_names,
                                     sec.out_names)

            def fwd(params, acts, feeds, _fn=fn):
                return _fn(params, *(list(acts) + list(feeds)))

            def bwd(params, acts, feeds, gouts, _fn=fn):
                def f(p, a):
                    return _fn(p, *(list(a) + list(feeds)))
                _, vjp = jax.vjp(f, params, tuple(acts))
                gp, ga = vjp(tuple(gouts))
                return gp, ga

            self._fwd.append(jax.jit(fwd))
            self._bwd.append(jax.jit(bwd))
        self._scope = scope

    def _put(self, val, s, replicate=False):
        """Place a value on stage s: its device, or — for a dp-submesh
        stage — sharded on the batch dim (params/scalars replicated)."""
        sh = self._stage_shardings[s]
        if sh is None:
            return jax.device_put(val, self.devices[s])
        batch_sh, repl_sh = sh
        if replicate or np.ndim(val) == 0:
            return jax.device_put(val, repl_sh)
        return jax.device_put(val, batch_sh)

    def _params(self, s):
        return {n: vb.value for n, vb in self._vbs[s].items()}

    def train_step(self, feed: Dict[str, np.ndarray]):
        """One optimizer step over ``num_microbatches`` slices of ``feed``.
        Returns the mean loss."""
        M = self.num_microbatches
        S = len(self.sections)
        for k, v in feed.items():
            if np.asarray(v).shape[0] % M:
                raise ValueError(
                    f"feed {k!r} batch {np.asarray(v).shape[0]} is not "
                    f"divisible by num_microbatches={M}; unequal "
                    f"microbatches would skew the 1/M gradient weighting")
        micro = []
        for m in range(M):
            micro.append({k: np.array_split(np.asarray(v), M)[m]
                          for k, v in feed.items()})

        # forward wave: boundary activations stashed per (stage, microbatch)
        stash_in: List[List] = [[None] * M for _ in range(S)]
        stash_feed: List[List] = [[None] * M for _ in range(S)]
        losses = [None] * M
        acts_by_name = [dict() for _ in range(M)]
        for m in range(M):
            for s, sec in enumerate(self.sections):
                acts = [self._put(acts_by_name[m][n], s)
                        for n in sec.in_names]
                feeds = [self._put(jnp.asarray(micro[m][n]), s)
                         for n in sec.feed_names]
                stash_in[s][m], stash_feed[s][m] = acts, feeds
                outs = self._fwd[s](self._params(s), acts, feeds)
                for n, v in zip(sec.out_names, outs):
                    acts_by_name[m][n] = v
                    if n == self.loss_name:
                        losses[m] = v

        # backward wave (reverse), mean-of-microbatch-losses objective
        gacc: List[Optional[Dict]] = [None] * S
        for m in range(M):
            gacts_by_name: Dict[str, jax.Array] = {}
            for s in range(S - 1, -1, -1):
                sec = self.sections[s]
                gouts = []
                for n in sec.out_names:
                    if n == self.loss_name:
                        g = jnp.full(np.shape(losses[m]), 1.0 / M,
                                     jnp.float32)
                    elif n in gacts_by_name:
                        g = self._put(gacts_by_name[n], s)
                    else:
                        g = jnp.zeros_like(acts_by_name[m][n])
                    gouts.append(g)
                gp, ga = self._bwd[s](self._params(s), stash_in[s][m],
                                      stash_feed[s][m], gouts)
                for n, v in zip(sec.in_names, ga):
                    # a boundary var can feed several later stages (skip
                    # connections): cotangents sum across consumers
                    if n in gacts_by_name:
                        prev = gacts_by_name[n]
                        gacts_by_name[n] = prev + jax.device_put(
                            v, prev.sharding)
                    else:
                        gacts_by_name[n] = v
                if gacc[s] is None:
                    gacc[s] = dict(gp)
                else:
                    gacc[s] = {n: gacc[s][n] + v for n, v in gp.items()}

        # optimizer apply per stage through the eager kernels
        from ..dygraph import base as dy_base
        with dy_base.guard():
            for s in range(S):
                vbs = self._vbs[s]
                for n, vb in vbs.items():
                    vb.grad = gacc[s][n]
                self.optimizer._dygraph_minimize(
                    None, parameter_list=list(vbs.values()))
                for vb in vbs.values():
                    vb.grad = None
        from ..framework.executor import _fetch_to_numpy
        return float(np.mean([_fetch_to_numpy(l) for l in losses]))

    def sync_to_scope(self):
        """Write stage params back to the scope (for save_persistables)."""
        for vbs in self._vbs:
            for n, vb in vbs.items():
                self._scope.set_var(n, jnp.asarray(vb.value))


class PipelineOptimizer:
    """ref ``python/paddle/fluid/optimizer.py:2687`` PipelineOptimizer.

    ``cut_list`` marks stage boundaries.  The reference's scheduler knobs
    (place_list/concurrency_list/queue_size/start_cpu_core_id) configure
    its section-worker threads; here XLA async dispatch schedules, so they
    are accepted for API parity and ignored.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=1):
        self._inner = optimizer
        self._cut_list = cut_list or []
        self._num_microbatches = num_microbatches
        self._sections: List[Section] = []
        self._loss_name: Optional[str] = None

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """Split the (forward) main program at cut_list.  Backward/apply
        happen functionally inside the engine — no grad ops are appended."""
        program = loss.block.program if hasattr(loss, "block") else \
            core.default_main_program()
        self._loss_name = loss.name if hasattr(loss, "name") else str(loss)
        self._sections = split_program(program, self._cut_list,
                                       self._loss_name)
        return [], []

    @property
    def sections(self):
        return self._sections

    def create_engine(self, devices=None, scope=None) -> PipelineEngine:
        """Build the runtime (after the startup program has run)."""
        if not self._sections:
            raise RuntimeError("call minimize(loss) first")
        return PipelineEngine(self._sections, self._loss_name, self._inner,
                              self._num_microbatches, devices, scope)
