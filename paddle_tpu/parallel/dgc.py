"""Deep Gradient Compression: top-k sparse allreduce with momentum
correction (ref SURVEY §2.5 DGC row: ``details/sparse_all_reduce_op_handle.cc``,
``operators/dgc_op.cc``, ``DGCMomentumOptimizer`` optimizer.py:809, external
lib ``cmake/external/dgc.cmake``).

Algorithm (Lin et al., the paper the reference's external DGC lib
implements), per device and per gradient:

    u = m*u + g                    # local momentum correction
    v = v + u                      # local gradient accumulation
    (idx, vals) = top_k(|v|, k)    # k = numel*(1-sparsity)
    sync: all-gather (idx, vals) over the dp axis, scatter-add, 1/n
    u, v zeroed at selected idx    # the rest stays local until it grows

The reference's ``SparseAllReduceOpHandle`` does exactly the all-gather of
encoded (idx, val) pairs over NCCL (``ncclAllGather`` in
sparse_all_reduce_op_handle.cc); here it is ``lax.all_gather`` over the
mesh axis — O(nranks·k) bytes over ICI instead of O(numel) for a dense
ring allreduce.  Before ``rampup_begin_step`` the op degrades to a dense
mean-gradient momentum step (the reference ramps sparsity up over
``rampup_step``; XLA needs a static k, so the schedule is a single
dense→sparse switch via ``lax.cond``).

The param update then is plain ``p -= lr * out`` (``dgc_momentum`` op) —
momentum already lives inside u.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ..ops.common import X
from ..distributed.collective_ops import _axis
from ..distributed.transpiler import Collective, OPTIMIZE_OPS


@register_op("dgc_allreduce", no_grad=True)
def _dgc_allreduce(ctx, ins, attrs):
    g = X(ins, "X")
    u = X(ins, "U")
    v = X(ins, "V")
    step = X(ins, "Step")
    ax = _axis(ctx, attrs)
    m = attrs.get("mu", 0.9)
    nesterov = bool(attrs.get("use_nesterov", False))
    sparsity = float(attrs.get("sparsity", 0.999))
    rampup = int(attrs.get("rampup_begin_step", 0))
    numel = int(np.prod(g.shape))
    k = max(1, int(round(numel * (1.0 - sparsity))))
    nranks = lax.psum(1, ax) if ax is not None else 1
    gf = g.reshape(-1).astype(jnp.float32)

    def dense_phase(u_, v_):
        mean_g = lax.psum(gf, ax) / nranks if ax is not None else gf
        u_new = m * u_ + mean_g
        out = mean_g + m * u_new if nesterov else u_new
        return out, u_new, v_

    def sparse_phase(u_, v_):
        # nesterov form per the DGC paper's correction: u = m*(u + g),
        # accumulate u + g; heavy-ball: u = m*u + g, accumulate u
        u_new = m * (u_ + gf) if nesterov else m * u_ + gf
        v_new = v_ + (u_new + gf if nesterov else u_new)
        _, idx = lax.top_k(jnp.abs(v_new), k)
        vals = v_new[idx]
        if ax is not None:
            g_idx = lax.all_gather(idx, ax).reshape(-1)
            g_vals = lax.all_gather(vals, ax).reshape(-1)
            dense = jnp.zeros_like(gf).at[g_idx].add(g_vals) / nranks
        else:
            dense = jnp.zeros_like(gf).at[idx].add(vals)
        keep = jnp.ones((numel,), jnp.float32).at[idx].set(0.0)
        return dense, u_new * keep, v_new * keep

    uf, vf = u.reshape(-1), v.reshape(-1)
    if rampup <= 0:
        out, u_out, v_out = sparse_phase(uf, vf)
    else:
        out, u_out, v_out = lax.cond(
            step.reshape(()) >= rampup,
            lambda uv: sparse_phase(*uv),
            lambda uv: dense_phase(*uv),
            (uf, vf))
    return {"Out": [out.reshape(g.shape).astype(g.dtype)],
            "UOut": [u_out], "VOut": [v_out],
            "StepOut": [(step + 1.0).astype(step.dtype)]}


@register_op("dgc_momentum", no_grad=True)
def _dgc_momentum(ctx, ins, attrs):
    """ref dgc_momentum_op.cc: momentum is folded into the DGC u buffer, so
    the param update is plain SGD on the compressed, corrected gradient."""
    p, g = X(ins, "Param"), X(ins, "Grad")
    lr = X(ins, "LearningRate")
    out = {"ParamOut": [p - lr.reshape(()) * g]}
    vel = X(ins, "Velocity")
    if vel is not None:
        out["VelocityOut"] = [vel]
    return out


@register_op("dgc_clip_by_norm", no_grad=True)
def _dgc_clip_by_norm(ctx, ins, attrs):
    """ref dgc_clip_by_norm_op.cc: local grad-norm clip before compression
    with the threshold rescaled by 1/sqrt(nranks) (each rank holds 1/n of
    the batch, so per-rank norms run smaller)."""
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    nranks = lax.psum(1, ax) if ax is not None else 1
    max_norm = attrs.get("max_norm", 1.0) / jnp.sqrt(float(nranks))
    norm = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return {"Out": [(x * scale).astype(x.dtype)]}


class DGCGradAllReduce(Collective):
    """Transpiler: rewrite DGC-tagged momentum ops into
    dgc_allreduce + dgc_momentum; remaining grads get the standard
    scale + c_allreduce_sum (ref build_strategy wiring of
    SparseAllReduceOpHandle next to plain AllReduceOpHandle)."""

    def transpile(self, startup_program=None, main_program=None, **kw):
        from ..framework import core
        self._startup = startup_program or core.default_startup_program()
        return super().transpile(startup_program, main_program, **kw)

    def _state_var(self, main_block, startup_block, name, shape, value=0.0):
        main_block.create_var(name=name, shape=shape, dtype="float32",
                              persistable=True)
        startup_block.create_var(name=name, shape=shape, dtype="float32",
                                 persistable=True)
        startup_block.append_op(
            "fill_constant", outputs={"Out": [name]},
            attrs={"shape": list(shape), "dtype": "float32",
                   "value": float(value)})

    def _transpile_main(self, main):
        block = main.global_block()
        sblock = self._startup.global_block()
        dgc_ops = []
        plain_grads = []
        first_opt = None
        for i, op in enumerate(block.ops):
            if op.type == "momentum" and op.attrs.get("dgc"):
                if first_opt is None:
                    first_opt = i
                dgc_ops.append(op)
            elif op.type in OPTIMIZE_OPS:
                if first_opt is None:
                    first_opt = i
                for g in op.input("Grad"):
                    if g and g not in plain_grads:
                        plain_grads.append(g)
        if first_opt is None:
            return
        at = first_opt
        for op in dgc_ops:
            g = op.input("Grad")[0]
            p = op.input("Param")[0]
            numel = int(np.prod(block.var(p).shape))
            u_n, v_n, s_n = (g + "@DGC_U", g + "@DGC_V", g + "@DGC_STEP")
            self._state_var(block, sblock, u_n, (numel,))
            self._state_var(block, sblock, v_n, (numel,))
            self._state_var(block, sblock, s_n, (1,))
            clip = op.attrs.get("local_grad_clip_norm")
            if clip is not None:
                block.insert_op(
                    at, "dgc_clip_by_norm",
                    inputs={"X": [g]}, outputs={"Out": [g]},
                    attrs={"max_norm": float(clip), "ring_id": 0})
                at += 1
            block.insert_op(
                at, "dgc_allreduce",
                inputs={"X": [g], "U": [u_n], "V": [v_n], "Step": [s_n]},
                outputs={"Out": [g], "UOut": [u_n], "VOut": [v_n],
                         "StepOut": [s_n]},
                attrs={"mu": op.attrs.get("mu", 0.9),
                       "use_nesterov": op.attrs.get("use_nesterov", False),
                       "sparsity": op.attrs.get("sparsity", 0.999),
                       "rampup_begin_step":
                       op.attrs.get("rampup_begin_step", 0),
                       "ring_id": 0})
            at += 1
            op.type = "dgc_momentum"
        self._append_dense_allreduce(block, at, plain_grads)
