from .mesh import hierarchical_allreduce, make_hierarchical_mesh  # noqa
from .mesh import (current_mesh, data_parallel_mesh, make_mesh,  # noqa
                   make_topology_mesh, mesh_axis_sizes, set_mesh,
                   sharding_for)
from .partitioner import (DEFAULT_RULE_TABLES, LogicalAxisRules,  # noqa
                          apply_rules, choose_rules, infer_logical_axes,
                          partition_fingerprint, partition_program,
                          rule_table)
from .pipeline import (PipelineEngine, PipelineOptimizer,  # noqa
                       Section, split_program)
from .dgc import DGCGradAllReduce  # noqa  (registers dgc_* op lowerings)
