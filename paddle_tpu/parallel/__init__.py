from .mesh import hierarchical_allreduce, make_hierarchical_mesh  # noqa
from .mesh import (current_mesh, data_parallel_mesh, make_mesh, set_mesh,  # noqa
                   sharding_for)
from .pipeline import (PipelineEngine, PipelineOptimizer,  # noqa
                       Section, split_program)
from .dgc import DGCGradAllReduce  # noqa  (registers dgc_* op lowerings)
