"""GSPMD model-parallel partitioner: logical-axis sharding rules lowered
through pjit (the T5X `logical axis rules` design, arxiv 2203.17189 §D —
see SNIPPETS.md [1]–[3]).

The reference repo scales exactly one way — one chip per replica
(``CompiledProgram.with_data_parallel``) — and its SURVEY names tensor
parallelism as the capability it lacks.  This module closes that gap on
top of the machinery PRs 7–15 built:

1. **Logical axis inference** (:func:`infer_logical_axes`): walk the
   dependency-ordered ``framework.ir`` Graph the same way the cost and
   int64 analyses do, and label every parameter dim with a LOGICAL axis
   name ("embed", "mlp", "heads", "kv", "vocab") from the op types that
   produce and consume it — a ``lookup_table`` weight is
   ``(vocab, embed)``, a matmul weight consumed from an embed-axis
   activation is column-parallel ``(embed, mlp|heads)``, its back
   projection is row-parallel ``(mlp|heads, embed)``, and the weight
   whose output feeds a cross-entropy is the ``vocab`` head.  No name
   matching: ``models.transformer.annotate_tensor_parallel`` hand-labels
   by suffix, this derives the same layout for ANY Fluid program.

2. **Rule tables** (:class:`LogicalAxisRules`): a named
   ``{logical axis -> mesh axis}`` map, e.g. ``{"heads": "mp", "mlp":
   "mp", "vocab": "mp"}``.  Applying a table turns inferred logical axes
   into ``dist_spec`` tuples (dropping dims the mesh can't divide), and
   stamps ``program._attrs["partition"]`` with the chosen table, the
   mesh shape, per-param PartitionSpecs and per-activation sharding
   constraints — the stamp rides ``Program.clone`` onto the optimized
   program, where the executor's trace applies
   ``with_sharding_constraint`` and the verifier folds it into the
   cross-rank collective fingerprint.

3. **Planner-driven selection** (:func:`choose_rules`): tables are
   ranked cheapest-communication-first; the static HBM planner
   (``analysis.memory.plan_sharded_memory``) evaluates each candidate's
   PER-SHARD peak and the first table fitting ``FLAGS_memory_budget_mb``
   wins, with the PR-13 analytic comm-vs-compute verdict ranking ties.
   The PR-15 runtime plane (``paddle_tpu_hbm_headroom_bytes``, the
   ``opt_state`` class gauge) then verifies the choice live.

ZeRO-1 optimizer-state sharding (arxiv 2004.13336) composes underneath:
``CompiledProgram.with_gspmd(zero_stage=1)`` additionally partitions
optimizer accumulators over the dp axis (``compiler._build_in_shardings``
resolves the accumulator's layout from its param via ``shard_like`` and
stacks ``dp`` on the free leading dim), so per-device optimizer bytes
drop by the data-parallel degree.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import monitor as _monitor

__all__ = [
    "LogicalAxisRules", "DEFAULT_RULE_TABLES", "rule_table",
    "infer_logical_axes", "apply_rules", "choose_rules",
    "partition_program", "partition_fingerprint",
]

#: planner decisions by outcome ("fit" = budget satisfied, "fallback" =
#: nothing fit and the most-sharded table was taken, "no_budget")
_CHOICE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_gspmd_rule_choices_total",
    "partitioner rule-table selections by planner outcome",
    ("rules", "outcome"))
_SHARD_PEAK_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_gspmd_per_shard_peak_bytes",
    "static per-shard HBM peak of the most recently chosen rule table")


@dataclass(frozen=True)
class LogicalAxisRules:
    """A named ``{logical axis -> mesh axis or None}`` table (SNIPPETS.md
    [1]/[3]: t5x ``logical_axis_rules`` / ``DEFAULT_RULES``).  ``None``
    keeps the logical axis replicated; axes absent from the table default
    to replicated too."""

    name: str
    rules: Dict[str, Optional[str]] = field(default_factory=dict)

    def mesh_axis(self, logical: Optional[str]) -> Optional[str]:
        if logical is None:
            return None
        return self.rules.get(logical)

    def token(self) -> str:
        return self.name + ":" + ",".join(
            f"{k}={v}" for k, v in sorted(self.rules.items()))


#: candidate tables, CHEAPEST COMMUNICATION FIRST — the planner walks
#: this order and takes the first table whose per-shard peak fits the
#: budget, so ties between fitting tables resolve toward less traffic.
DEFAULT_RULE_TABLES: Tuple[LogicalAxisRules, ...] = (
    # pure DP: params replicated, batch over dp (the with_data_parallel
    # layout, expressed as the empty rule table)
    LogicalAxisRules("replicated", {"batch": "dp"}),
    # Megatron block sharding: attention heads + FFN hidden over mp;
    # embed stays replicated so layer boundaries need no resharding
    LogicalAxisRules("mp_hidden", {
        "batch": "dp", "heads": "mp", "kv": "mp", "mlp": "mp"}),
    # + vocab-sharded embedding/LM head: the biggest params shard too
    # (more allreduce traffic: embedding gather + logits reduction)
    LogicalAxisRules("mp_hidden_vocab", {
        "batch": "dp", "heads": "mp", "kv": "mp", "mlp": "mp",
        "vocab": "mp"}),
)


def rule_table(name_or_rules) -> LogicalAxisRules:
    """Resolve a rule table: a :class:`LogicalAxisRules` passes through,
    a dict becomes an ad-hoc table, a string names a default table."""
    if isinstance(name_or_rules, LogicalAxisRules):
        return name_or_rules
    if isinstance(name_or_rules, dict):
        return LogicalAxisRules("custom", dict(name_or_rules))
    for t in DEFAULT_RULE_TABLES:
        if t.name == name_or_rules:
            return t
    raise ValueError(
        f"unknown rule table {name_or_rules!r}; known: "
        f"{[t.name for t in DEFAULT_RULE_TABLES]} (or pass a "
        "{logical_axis: mesh_axis} dict)")


# ---------------------------------------------------------------------------
# logical-axis inference over the ir Graph
# ---------------------------------------------------------------------------

#: ops that preserve the last-dim logical axis of their first input
_PROPAGATE = frozenset((
    "relu", "gelu", "tanh", "sigmoid", "softmax", "dropout", "scale",
    "layer_norm", "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "cast", "sum", "concat", "fused_bias_act",
    "fused_dense_act",
))

#: loss ops whose logits input chain marks the vocab projection
_CE_OPS = frozenset((
    "cross_entropy", "softmax_with_cross_entropy", "fused_lm_head_ce",
))


def _is_param(block, name) -> bool:
    return (name and block.has_var(name)
            and getattr(block.var(name), "is_parameter", False))


def _first(names):
    return names[0] if names else None


def infer_logical_axes(program) -> Dict[str, Tuple[Optional[str], ...]]:
    """Per-parameter logical axis names, one per dim (None = no logical
    identity → always replicated).  Walks ops in the ir Graph's
    dependency order propagating the last-dim logical axis of every
    activation (the cost/int64 analyses' walking discipline)."""
    from ..framework import ir
    block = program.global_block()
    order = ir.Graph(program).topology_sort()

    axes: Dict[str, Tuple[Optional[str], ...]] = {}
    act: Dict[str, Optional[str]] = {}     # var -> last-dim logical axis
    produced_by: Dict[str, object] = {}    # var -> producing op (for CE pass)

    def consumers(name):
        out = []
        for node in order:
            op = node.op
            if any(name in ns for ns in op.inputs.values()):
                out.append(op)
        return out

    # head-split detection: a matmul output whose (activation-chain)
    # consumers include a reshape ADDING a trailing dim is a q/k/v
    # projection — label its out axis "heads" instead of "mlp"
    def _feeds_head_split(name, depth=3):
        if depth <= 0:
            return False
        for op in consumers(name):
            if op.type in ("reshape", "reshape2"):
                shape = op.attrs.get("shape") or ()
                src = _first(op.inputs.get("X", []))
                if (src and block.has_var(src)
                        and block.var(src).shape is not None
                        and len(shape) > len(block.var(src).shape)):
                    return True
            elif op.type in _PROPAGATE:
                out = _first(op.outputs.get("Out", []))
                if out and _feeds_head_split(out, depth - 1):
                    return True
        return False

    for node in order:
        op = node.op
        t = op.type
        if t == "lookup_table":
            w = _first(op.inputs.get("W", []))
            out = _first(op.outputs.get("Out", []))
            if _is_param(block, w):
                axes[w] = ("vocab", "embed")
            if out:
                act[out] = "embed"
                produced_by[out] = op
        elif t in ("mul", "matmul", "matmul_v2"):
            x = _first(op.inputs.get("X", []))
            y = _first(op.inputs.get("Y", []))
            out = _first(op.outputs.get("Out", []))
            if not _is_param(block, y):
                # activation×activation matmul (attention scores): the
                # product's last dim carries no parameter identity
                if out:
                    act[out] = None
                    produced_by[out] = op
                continue
            yshape = tuple(block.var(y).shape or ())
            in_ax = act.get(x, "embed")
            if in_ax in ("mlp", "heads", "kv"):
                out_ax = "embed"               # row-parallel projection
            elif _feeds_head_split(out) if out else False:
                out_ax = "heads"               # q/k/v column projection
            else:
                out_ax = "mlp"                 # FFN column projection
            yaxes = (in_ax, out_ax)
            if op.attrs.get("transpose_Y"):
                yaxes = (out_ax, in_ax)
            if len(yshape) == len(yaxes):
                axes[y] = yaxes
            if out:
                act[out] = out_ax
                produced_by[out] = op
        elif t in _PROPAGATE:
            x = _first(op.inputs.get("X", []))
            out = _first(op.outputs.get("Out", []))
            # a rank-1 parameter on an elementwise op is a bias/scale
            # vector along the activation's last-dim axis
            for slot in ("Y", "Scale", "Bias"):
                p = _first(op.inputs.get(slot, []))
                if _is_param(block, p) and \
                        len(block.var(p).shape or ()) == 1:
                    ax = act.get(x, "embed" if t == "layer_norm" else None)
                    axes.setdefault(p, (ax,))
            if out:
                act[out] = act.get(x)
                produced_by[out] = op
        elif t in ("reshape", "reshape2", "transpose", "transpose2"):
            x = _first(op.inputs.get("X", []))
            out = _first(op.outputs.get("Out", []))
            if out:
                # conservatively drop the label across layout changes —
                # a wrong axis here would constrain activations wrongly
                act[out] = act.get(x) if t.startswith("reshape") else None
                produced_by[out] = op

    # vocab head pass: the matmul feeding a cross-entropy projects onto
    # the vocabulary — relabel its weight's OUT axis (and its bias)
    for node in order:
        op = node.op
        if op.type not in _CE_OPS:
            continue
        slot = "Logits" if "Logits" in op.inputs else "X"
        name = _first(op.inputs.get(slot, []))
        for _ in range(6):              # walk back through the act chain
            src = produced_by.get(name)
            if src is None:
                break
            if src.type in ("mul", "matmul", "matmul_v2"):
                y = _first(src.inputs.get("Y", []))
                if _is_param(block, y) and y in axes:
                    a0, a1 = axes[y]
                    axes[y] = (a0, "vocab") if not \
                        src.attrs.get("transpose_Y") else ("vocab", a1)
                    b = _first(src.outputs.get("Out", []))
                    for bop in consumers(b):
                        if bop.type == "elementwise_add":
                            p = _first(bop.inputs.get("Y", []))
                            if _is_param(block, p):
                                axes[p] = ("vocab",)
                break
            name = _first(src.inputs.get("X", []))
    return axes


# ---------------------------------------------------------------------------
# rule application
# ---------------------------------------------------------------------------

def _spec_for(shape, logical, table: LogicalAxisRules,
              axis_sizes: Dict[str, int], dropped=None, name=None):
    """dist_spec tuple for one var, or None (fully replicated).  A dim
    stays replicated when its logical axis is unmapped, the mesh axis is
    absent/trivial, or the static dim doesn't divide evenly (GSPMD could
    pad, but the memory planner's per-shard arithmetic — and ZeRO-1's
    scope layout — want exact shards).  A non-dividing MAPPED dim is the
    silent-drop case: when ``dropped`` is a list, each such dim appends
    ``(name, dim, logical_axis, mesh_axis, dim_size, axis_size)`` so the
    drop surfaces as a ``shard_divisibility`` diagnostic instead of
    vanishing."""
    spec = []
    for i, (d, ax) in enumerate(zip(shape, logical)):
        m = table.mesh_axis(ax)
        size = axis_sizes.get(m, 0) if m else 0
        if m and size > 1 and isinstance(d, int) and d > 0 \
                and d % size == 0:
            spec.append(m)
        else:
            if dropped is not None and m and size > 1 \
                    and isinstance(d, int) and d > 0:
                dropped.append((name, i, ax, m, int(d), int(size)))
            spec.append(None)
    return tuple(spec) if any(s is not None for s in spec) else None


def apply_rules(program, table, axis_sizes: Dict[str, int],
                logical_axes=None) -> dict:
    """Set ``Variable.dist_spec`` on every inferred parameter per
    ``table`` and stamp ``program._attrs["partition"]`` (table name,
    mesh shape, per-param specs, per-activation sharding constraints).
    Returns the stamp.  Idempotent per (table, mesh)."""
    table = rule_table(table)
    block = program.global_block()
    logical = logical_axes if logical_axes is not None else \
        infer_logical_axes(program)

    params: Dict[str, tuple] = {}
    dropped: List[tuple] = []
    for name, laxes in sorted(logical.items()):
        if not block.has_var(name):
            continue
        v = block.var(name)
        shape = tuple(v.shape or ())
        if len(shape) != len(laxes):
            continue
        spec = _spec_for(shape, laxes, table, axis_sizes,
                         dropped=dropped, name=name)
        v.dist_spec = spec
        if spec is not None:
            params[name] = spec

    # activation constraints: batch dim on dp, last dim per its logical
    # axis — GSPMD would propagate most of these, the explicit
    # constraint pins the layout the planner priced (t5x
    # with_sharding_constraint discipline, SNIPPETS.md [1])
    acts: Dict[str, tuple] = {}
    dp = "dp" if axis_sizes.get("dp", 0) > 1 else None
    act_axis = _activation_axes(program, logical)
    for name, last_ax in act_axis.items():
        if not block.has_var(name):
            continue
        v = block.var(name)
        shape = v.shape
        if v.persistable or getattr(v, "is_data", False) or \
                shape is None or len(shape) < 2:
            continue
        last = table.mesh_axis(last_ax)
        lsize = axis_sizes.get(last, 0) if last else 0
        last_ok = (last and lsize > 1 and isinstance(shape[-1], int)
                   and shape[-1] > 0 and shape[-1] % lsize == 0)
        spec = (dp,) + (None,) * (len(shape) - 2) + \
            (last if last_ok else None,)
        if any(s is not None for s in spec):
            acts[name] = spec

    stamp = {
        "rules": table.name,
        "rules_token": table.token(),
        "mesh_axes": {a: int(s) for a, s in sorted(axis_sizes.items())},
        "params": params,
        "activations": acts,
        # dims the divisibility guard kept replicated even though the
        # table MAPS them — surfaced by the shard_divisibility check
        # (analysis.sharding) instead of dropped silently
        "dropped": dropped,
    }
    program._attrs["partition"] = stamp
    _warn_dropped_dims(stamp)
    return stamp


#: partition fingerprints whose divisibility drops were already warned —
#: once per (table, mesh, specs), not once per re-apply
_DROP_WARNED: set = set()  # guarded-by: _DROP_WARNED_LOCK
_DROP_WARNED_LOCK = threading.Lock()


def _warn_dropped_dims(stamp) -> None:
    """One ``warnings.warn`` per partition fingerprint when the
    divisibility guard dropped mapped dims, formatted through the
    debugger's diagnostic renderer (the verify stamp carries the same
    findings; this warning is the interactive surface)."""
    dropped = stamp.get("dropped")
    if not dropped:
        return
    fp = partition_fingerprint(stamp)
    with _DROP_WARNED_LOCK:
        if fp in _DROP_WARNED:
            return
        _DROP_WARNED.add(fp)
    from .. import debugger
    from ..analysis.verifier import Diagnostic
    diags = [
        Diagnostic(
            check="shard_divisibility", severity="warning",
            message=(
                f"dim {dim} of {name!r} (size {dsize}, logical axis "
                f"{lax!r}) does not divide mesh axis {max_!r} "
                f"(size {asize}): kept REPLICATED"),
            var=name,
            fix_hint=(f"pad {name!r} to a multiple of {asize} along "
                      f"dim {dim}, or unmap {lax!r} in the rule table"))
        for name, dim, lax, max_, dsize, asize in dropped]
    import warnings
    warnings.warn(
        f"GSPMD rule table {stamp.get('rules')!r} silently drops "
        f"{len(dropped)} mapped dim(s):\n"
        + debugger.format_diagnostics(diags), stacklevel=3)


def _activation_axes(program, logical_axes) -> Dict[str, Optional[str]]:
    """Last-dim logical axis per activation var — a second, lighter walk
    sharing :func:`infer_logical_axes`'s propagation rules (returned
    separately so apply_rules can re-run under a different table without
    re-inferring)."""
    from ..framework import ir
    block = program.global_block()
    act: Dict[str, Optional[str]] = {}
    for node in ir.Graph(program).topology_sort():
        op = node.op
        t = op.type
        out = _first(op.outputs.get("Out", []))
        if not out:
            continue
        if t == "lookup_table":
            act[out] = "embed"
        elif t in ("mul", "matmul", "matmul_v2"):
            y = _first(op.inputs.get("Y", []))
            if _is_param(block, y) and y in logical_axes:
                laxes = logical_axes[y]
                act[out] = laxes[0] if op.attrs.get("transpose_Y") \
                    else laxes[-1]
            else:
                act[out] = None
        elif t in _PROPAGATE or t in ("reshape", "reshape2"):
            act[out] = act.get(_first(op.inputs.get("X", [])))
    return act


# ---------------------------------------------------------------------------
# planner-driven table selection
# ---------------------------------------------------------------------------

def _est_comm_ms(program, table: LogicalAxisRules, logical_axes,
                 axis_sizes, batch_size: int) -> float:
    """Analytic per-step GSPMD collective traffic for one rule table —
    the PR-13 ring model applied to the collectives the SPMD partitioner
    will insert: a row-parallel (contracting-dim-sharded) matmul
    all-reduces its output partials in forward AND its input grads in
    backward; a column-parallel one all-reduces dX in backward only; a
    vocab-sharded table gathers its lookups.  Coarse by design (the
    planner only needs a consistent ranking), priced at the ICI link
    peak like ``analysis.comms``."""
    from ..analysis.comms import device_link_bandwidth
    block = program.global_block()
    mp = axis_sizes.get("mp", 1)
    if mp <= 1:
        return 0.0
    ring = 2.0 * (mp - 1) / mp
    bw = device_link_bandwidth()
    total = 0.0
    for name, laxes in logical_axes.items():
        if not block.has_var(name):
            continue
        shape = tuple(block.var(name).shape or ())
        if len(shape) != len(laxes) or len(shape) != 2:
            continue
        spec = _spec_for(shape, laxes, table, axis_sizes)
        if spec is None:
            continue
        d_in, d_out = shape
        if laxes == ("vocab", "embed"):
            # sharded embedding: gather fwd + scatter-add bwd of
            # [batch, embed] activations
            total += 2 * batch_size * d_out * 4
            continue
        if spec[0] == "mp":
            total += 2 * batch_size * d_out * 4     # partial-sum psum ×2
        if spec[1] == "mp":
            total += batch_size * d_in * 4          # bwd dX allreduce
    return total * ring / bw * 1e3


def choose_rules(program, axis_sizes: Dict[str, int], fetch_names=(),
                 batch_size: int = 1, candidates=None,
                 budget_mb: Optional[float] = None):
    """Planner-driven rule-table selection (module docstring §3).

    Evaluates every candidate's PER-SHARD static peak
    (``analysis.memory.plan_sharded_memory``) and picks the FIRST —
    i.e. cheapest-communication — table fitting the budget
    (``FLAGS_memory_budget_mb`` unless overridden); among candidates the
    walk cannot separate, the analytic comm-vs-compute verdict ranks
    (compute-bound beats comm-bound, then lower est ms).  With no
    budget, the least-communication table wins outright.  Returns
    ``(LogicalAxisRules, report)`` where ``report`` is the per-candidate
    evaluation (stamped into the partition attrs by
    :func:`partition_program` so the choice is auditable)."""
    from ..analysis.cost import device_peak_flops, plan_cost
    from ..analysis.memory import plan_sharded_memory
    from ..flags import get_flags

    if budget_mb is None:
        budget_mb = float(
            get_flags("FLAGS_memory_budget_mb")["FLAGS_memory_budget_mb"])
    budget = float(budget_mb) * (1 << 20) if budget_mb else None
    cands = [rule_table(c) for c in
             (candidates if candidates is not None else
              DEFAULT_RULE_TABLES)]
    logical = infer_logical_axes(program)
    act_axis = _activation_axes(program, logical)
    block = program.global_block()
    try:
        compute_ms = plan_cost(program, fetch_names,
                               batch_size=batch_size).flops \
            / device_peak_flops() * 1e3
    except Exception:
        compute_ms = 0.0

    report: List[dict] = []
    for table in cands:
        specs: Dict[str, tuple] = {}
        for name, laxes in logical.items():
            if not block.has_var(name):
                continue
            shape = tuple(block.var(name).shape or ())
            if len(shape) != len(laxes):
                continue
            spec = _spec_for(shape, laxes, table, axis_sizes)
            if spec is not None:
                specs[name] = spec
        dp = "dp" if axis_sizes.get("dp", 0) > 1 else None
        for name, last_ax in act_axis.items():
            if name in specs or not block.has_var(name):
                continue
            v = block.var(name)
            shape = v.shape
            if v.persistable or shape is None or len(shape) < 2:
                continue
            last = table.mesh_axis(last_ax)
            lsize = axis_sizes.get(last, 0) if last else 0
            spec = [dp] + [None] * (len(shape) - 1)
            if last and lsize > 1 and isinstance(shape[-1], int) \
                    and shape[-1] > 0 and shape[-1] % lsize == 0:
                spec[-1] = last
            if any(spec):
                specs[name] = tuple(spec)
        plan = plan_sharded_memory(program, fetch_names,
                                   batch_size=batch_size, specs=specs,
                                   axis_sizes=axis_sizes)
        # price the candidate on its REAL per-edge reshard plan
        # (analysis.sharding: every implicit collective the SPMD
        # partitioner will insert, ring-priced); the pre-PR-20 matmul
        # heuristic stays as the fallback when the pass cannot plan
        resh = None
        try:
            from ..analysis.sharding import plan_sharding
            resh = plan_sharding(program, fetch_names,
                                 batch_size=batch_size, specs=specs,
                                 axis_sizes=axis_sizes,
                                 rules=table.name)
        except Exception:
            resh = None
        if resh is not None:
            comm_ms = resh.est_ms
        else:
            comm_ms = _est_comm_ms(program, table, logical, axis_sizes,
                                   batch_size)
        report.append({
            "rules": table.name,
            "per_shard_peak_bytes": int(plan.peak_bytes),
            "per_shard_steady_bytes": int(plan.steady_bytes),
            "fits": bool(budget is None or plan.peak_bytes <= budget),
            "est_comm_ms": round(comm_ms, 4),
            "est_compute_ms": round(compute_ms, 4),
            "bound": "comm" if comm_ms > compute_ms else "compute",
            "sharded_params": len(specs),
            "reshard_edges": None if resh is None else len(resh.edges),
            "reshard_bytes": None if resh is None
            else int(resh.payload_bytes),
            "reshard_wire_bytes": None if resh is None
            else int(resh.wire_bytes),
            "reshard_fingerprint": None if resh is None
            else resh.fingerprint,
        })

    if budget is None:
        chosen, outcome = 0, "no_budget"
    else:
        fits = [i for i, r in enumerate(report) if r["fits"]]
        if fits:
            outcome = "fit"
            # candidate order is cheapest-comm-first; the verdict ranks
            # the survivors so a compute-bound table beats a comm-bound
            # one even when the walk order says otherwise
            chosen = min(fits, key=lambda i: (
                report[i]["bound"] == "comm",
                report[i]["est_comm_ms"], i))
        else:
            # nothing fits: take the smallest per-shard peak — training
            # may still OOM, but this is the best static answer, and the
            # report says so
            chosen = min(range(len(report)),
                         key=lambda i: report[i]["per_shard_peak_bytes"])
            outcome = "fallback"
    for i, r in enumerate(report):
        r["chosen"] = (i == chosen)
    _CHOICE_CTR.inc(1, rules=cands[chosen].name, outcome=outcome)
    _SHARD_PEAK_GAUGE.set(float(report[chosen]["per_shard_peak_bytes"]))
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant(
            "gspmd.choose_rules", "compile",
            {"outcome": outcome, "report": report,
             "budget_mb": budget_mb})
    return cands[chosen], report


def partition_program(program, axis_sizes: Dict[str, int], rules="auto",
                      fetch_names=(), batch_size: int = 1,
                      budget_mb: Optional[float] = None) -> dict:
    """One-call entry: select (``rules="auto"``) or resolve a rule
    table, apply it to ``program`` and return the partition stamp (with
    the planner report attached under ``"planner"`` when auto)."""
    logical = infer_logical_axes(program)
    if rules == "auto":
        table, rep = choose_rules(program, axis_sizes,
                                  fetch_names=fetch_names,
                                  batch_size=batch_size,
                                  budget_mb=budget_mb)
    else:
        table, rep = rule_table(rules), None
    stamp = apply_rules(program, table, axis_sizes, logical_axes=logical)
    if rep is not None:
        stamp["planner"] = rep
    return stamp


def partition_fingerprint(stamp: Optional[dict]) -> Optional[str]:
    """Deterministic token of one partition stamp: mesh shape + sorted
    per-param PartitionSpecs, suffixed ``#rules=<table>`` so a cross-rank
    refusal NAMES both rule tables (the coordinator's mismatch detail
    prints both fingerprints verbatim)."""
    if not stamp:
        return None
    body = repr((sorted((stamp.get("mesh_axes") or {}).items()),
                 sorted((stamp.get("params") or {}).items()),
                 int(stamp.get("zero_stage") or 0)))
    return (hashlib.sha1(body.encode()).hexdigest()
            + f"#rules={stamp.get('rules')}")
