"""Profiler API (ref ``python/paddle/fluid/profiler.py`` +
``platform/profiler.h:81,166`` RecordEvent/EnableProfiler).

Host-side timing runs through the native C++ profiler
(``native/src/profiler.cc`` — thread-local event lists, chrome-trace export,
the reference's design) with a pure-Python fallback; device-side profiling
delegates to ``jax.profiler`` (XLA's TraceMe ≈ the reference's CUPTI
device tracer), matching SURVEY §5.1's TPU mapping.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

from . import native

_py_events = []          # fallback store: (name, tid, start_ns, end_ns)
_py_open = threading.local()
_py_enabled = False
_use_native = None

#: wall↔monotonic anchor pair so RecordEvent timestamps (monotonic) land
#: on the step tracer's epoch-aligned axis in one merged chrome trace —
#: without it the two sources sit decades apart on the timeline
_WALL0 = time.time()
_MONO0 = time.monotonic_ns()
#: wall time at the last native-profiler enable/reset: the native trace
#: stamps steady-clock ns relative to its enable epoch, so adding this
#: anchor converts it to the same epoch axis
_native_epoch_wall = _WALL0


def _mono_ns_to_epoch_us(t_ns: int) -> float:
    return (_WALL0 + (t_ns - _MONO0) / 1e9) * 1e6


def _native_ok() -> bool:
    global _use_native
    if _use_native is None:
        _use_native = native.available()
    return _use_native


def is_profiler_enabled() -> bool:
    if _native_ok():
        return native.NativeProfiler.is_enabled()
    return _py_enabled


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """ref profiler.py start_profiler — state/tracer args accepted for
    parity; host events always recorded, device via jax.profiler."""
    global _py_enabled, _native_epoch_wall
    if _native_ok():
        _native_epoch_wall = time.time()
        native.NativeProfiler.enable()
    else:
        _py_enabled = True


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    """ref profiler.py stop_profiler — prints the aggregate table and
    optionally writes a chrome trace."""
    global _py_enabled
    report = profiler_report()
    if profile_path:
        chrome_trace(profile_path)
    if _native_ok():
        native.NativeProfiler.disable()
    else:
        _py_enabled = False
    _print_report(report, sorted_key)
    return report


def reset_profiler():
    # NB: the native epoch anchor is only re-stamped on enable (the C++
    # side stores g_epoch_ns in ptn_profiler_enable, not reset)
    global _py_events
    if _native_ok():
        native.NativeProfiler.reset()
    else:
        _py_events = []


def profiler_report() -> dict:
    if _native_ok():
        return native.NativeProfiler.report()
    agg = {}
    for name, tid, s, e in _py_events:
        a = agg.setdefault(name, {"calls": 0, "total_us": 0.0,
                                  "min_us": float("inf"), "max_us": 0.0})
        d = (e - s) / 1000.0
        a["calls"] += 1
        a["total_us"] += d
        a["min_us"] = min(a["min_us"], d)
        a["max_us"] = max(a["max_us"], d)
    return agg


def chrome_trace(path: str) -> bool:
    """Write chrome://tracing JSON (ref tools/timeline.py output).

    Merges BOTH event sources into one timeline: classic RecordEvent
    profiler events (native or py fallback) and the step tracer's async-
    pipeline spans (``monitor.TRACER`` — dataloader staging, compile,
    dispatch/throttle, fetch materialization, collectives).  Per-rank
    files then stack via ``tools/timeline.py``."""
    from . import monitor as _monitor
    tracer_events = _monitor.TRACER.chrome_events()
    if _native_ok():
        ok = native.NativeProfiler.chrome_trace(path)
        if ok:
            if tracer_events:
                with open(path) as f:
                    data = json.load(f)
                evs = data if isinstance(data, list) else \
                    data.setdefault("traceEvents", [])
                # native timestamps are steady-clock us since the last
                # enable; shift onto the tracer's epoch axis so both
                # sources share one timeline
                shift = _native_epoch_wall * 1e6
                for ev in evs:
                    if "ts" in ev:
                        ev["ts"] = ev["ts"] + shift
                evs.extend(tracer_events)
                with open(path, "w") as f:
                    json.dump(data if isinstance(data, dict)
                              else {"traceEvents": evs}, f)
            return True
        events = []
    else:
        # epoch-aligned (same axis as the tracer spans)
        events = [{"name": n, "ph": "X", "pid": os.getpid(), "tid": t,
                   "ts": _mono_ns_to_epoch_us(s),
                   "dur": (e - s) / 1000.0}
                  for n, t, s, e in _py_events]
    events.extend(tracer_events)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return True


def _print_report(report: dict, sorted_key: Optional[str]):
    if not report:
        return
    key = {"calls": lambda kv: -kv[1]["calls"],
           "total": lambda kv: -kv[1]["total_us"],
           "max": lambda kv: -kv[1]["max_us"],
           "min": lambda kv: kv[1]["min_us"]}.get(
               sorted_key or "total", lambda kv: -kv[1]["total_us"])
    rows = sorted(report.items(), key=key)
    print(f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}"
          f"{'Min(us)':>12}{'Max(us)':>12}{'Ave(us)':>12}")
    for name, a in rows:
        print(f"{name:<40}{a['calls']:>8}{a['total_us']:>14.1f}"
              f"{a['min_us']:>12.1f}{a['max_us']:>12.1f}"
              f"{a['total_us'] / max(a['calls'], 1):>12.1f}")


class RecordEvent:
    """RAII/context event marker (ref platform/profiler.h:81)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        if _native_ok():
            if native.NativeProfiler.is_enabled():
                native.NativeProfiler.event_begin(self.name)
                self._rec = True
            else:
                self._rec = False
        elif _py_enabled:
            stack = getattr(_py_open, "stack", None)
            if stack is None:
                stack = _py_open.stack = []
            stack.append((self.name, time.monotonic_ns()))
            self._rec = True
        else:
            self._rec = False
        return self

    def __exit__(self, *exc):
        if not self._rec:
            return False
        if _native_ok():
            native.NativeProfiler.event_end()
        else:
            name, start = _py_open.stack.pop()
            _py_events.append((name, threading.get_ident() & 0xffffff,
                               start, time.monotonic_ns()))
        return False


record_event = RecordEvent


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """``with fluid.profiler.profiler(...):`` (ref profiler.py:profiler)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def dispatch_stats() -> dict:
    """Aggregate executor dispatch counters across all live executors —
    the steady-state 'framework tax' ledger: compiled-block cache
    hits/misses, re-lowerings (``traces``), steps dispatched, host
    time-to-dispatch, and host-block time split by cause (fetch
    materialization / in-flight throttle / FLAGS_benchmark sync).  The
    per-executor view is ``Executor.dispatch_stats()``; this one sums
    them plus an ``executors`` count, so a training script can report
    dispatch overhead without holding executor references."""
    from .framework import executor as _executor
    return _executor.aggregate_dispatch_stats()


@contextlib.contextmanager
def device_profiler(logdir: str):
    """XLA/TPU device profile via jax.profiler (≈ CUPTI device tracer);
    view with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
