"""Profiler API (ref ``python/paddle/fluid/profiler.py`` +
``platform/profiler.h:81,166`` RecordEvent/EnableProfiler).

Host-side timing runs through the native C++ profiler
(``native/src/profiler.cc`` — thread-local event lists, chrome-trace export,
the reference's design) with a pure-Python fallback; device-side profiling
delegates to ``jax.profiler`` (XLA's TraceMe ≈ the reference's CUPTI
device tracer), matching SURVEY §5.1's TPU mapping.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

from . import native

_py_events = []          # fallback store: (name, tid, start_ns, end_ns)
_py_open = threading.local()
_py_enabled = False
_use_native = None

#: wall↔monotonic anchor pair so RecordEvent timestamps (monotonic) land
#: on the step tracer's epoch-aligned axis in one merged chrome trace —
#: without it the two sources sit decades apart on the timeline
_WALL0 = time.time()
_MONO0 = time.monotonic_ns()
#: wall time at the last native-profiler enable/reset: the native trace
#: stamps steady-clock ns relative to its enable epoch, so adding this
#: anchor converts it to the same epoch axis
_native_epoch_wall = _WALL0


def _mono_ns_to_epoch_us(t_ns: int) -> float:
    return (_WALL0 + (t_ns - _MONO0) / 1e9) * 1e6


def _native_ok() -> bool:
    global _use_native
    if _use_native is None:
        _use_native = native.available()
    return _use_native


def is_profiler_enabled() -> bool:
    if _native_ok():
        return native.NativeProfiler.is_enabled()
    return _py_enabled


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """ref profiler.py start_profiler — state/tracer args accepted for
    parity; host events always recorded, device via jax.profiler."""
    global _py_enabled, _native_epoch_wall
    if _native_ok():
        _native_epoch_wall = time.time()
        native.NativeProfiler.enable()
    else:
        _py_enabled = True


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    """ref profiler.py stop_profiler — prints the aggregate table and
    optionally writes a chrome trace."""
    global _py_enabled
    report = profiler_report()
    if profile_path:
        chrome_trace(profile_path)
    if _native_ok():
        native.NativeProfiler.disable()
    else:
        _py_enabled = False
    _print_report(report, sorted_key)
    return report


def reset_profiler():
    # NB: the native epoch anchor is only re-stamped on enable (the C++
    # side stores g_epoch_ns in ptn_profiler_enable, not reset)
    global _py_events
    if _native_ok():
        native.NativeProfiler.reset()
    else:
        _py_events = []


def profiler_report() -> dict:
    if _native_ok():
        return native.NativeProfiler.report()
    agg = {}
    for name, tid, s, e in _py_events:
        a = agg.setdefault(name, {"calls": 0, "total_us": 0.0,
                                  "min_us": float("inf"), "max_us": 0.0})
        d = (e - s) / 1000.0
        a["calls"] += 1
        a["total_us"] += d
        a["min_us"] = min(a["min_us"], d)
        a["max_us"] = max(a["max_us"], d)
    return agg


def chrome_trace(path: str) -> bool:
    """Write chrome://tracing JSON (ref tools/timeline.py output).

    Merges BOTH event sources into one timeline: classic RecordEvent
    profiler events (native or py fallback) and the step tracer's async-
    pipeline spans (``monitor.TRACER`` — dataloader staging, compile,
    dispatch/throttle, fetch materialization, collectives).  Per-rank
    files then stack via ``tools/timeline.py``."""
    from . import monitor as _monitor
    tracer_events = _monitor.TRACER.chrome_events()
    if _native_ok():
        ok = native.NativeProfiler.chrome_trace(path)
        if ok:
            if tracer_events:
                with open(path) as f:
                    data = json.load(f)
                evs = data if isinstance(data, list) else \
                    data.setdefault("traceEvents", [])
                # native timestamps are steady-clock us since the last
                # enable; shift onto the tracer's epoch axis so both
                # sources share one timeline
                shift = _native_epoch_wall * 1e6
                for ev in evs:
                    if "ts" in ev:
                        ev["ts"] = ev["ts"] + shift
                evs.extend(tracer_events)
                with open(path, "w") as f:
                    json.dump(data if isinstance(data, dict)
                              else {"traceEvents": evs}, f)
            return True
        events = []
    else:
        # epoch-aligned (same axis as the tracer spans)
        events = [{"name": n, "ph": "X", "pid": os.getpid(), "tid": t,
                   "ts": _mono_ns_to_epoch_us(s),
                   "dur": (e - s) / 1000.0}
                  for n, t, s, e in _py_events]
    events.extend(tracer_events)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return True


def _print_report(report: dict, sorted_key: Optional[str]):
    if not report:
        return
    key = {"calls": lambda kv: -kv[1]["calls"],
           "total": lambda kv: -kv[1]["total_us"],
           "max": lambda kv: -kv[1]["max_us"],
           "min": lambda kv: kv[1]["min_us"]}.get(
               sorted_key or "total", lambda kv: -kv[1]["total_us"])
    rows = sorted(report.items(), key=key)
    print(f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}"
          f"{'Min(us)':>12}{'Max(us)':>12}{'Ave(us)':>12}")
    for name, a in rows:
        print(f"{name:<40}{a['calls']:>8}{a['total_us']:>14.1f}"
              f"{a['min_us']:>12.1f}{a['max_us']:>12.1f}"
              f"{a['total_us'] / max(a['calls'], 1):>12.1f}")


class RecordEvent:
    """RAII/context event marker (ref platform/profiler.h:81)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        if _native_ok():
            if native.NativeProfiler.is_enabled():
                native.NativeProfiler.event_begin(self.name)
                self._rec = True
            else:
                self._rec = False
        elif _py_enabled:
            stack = getattr(_py_open, "stack", None)
            if stack is None:
                stack = _py_open.stack = []
            stack.append((self.name, time.monotonic_ns()))
            self._rec = True
        else:
            self._rec = False
        return self

    def __exit__(self, *exc):
        if not self._rec:
            return False
        if _native_ok():
            native.NativeProfiler.event_end()
        else:
            name, start = _py_open.stack.pop()
            _py_events.append((name, threading.get_ident() & 0xffffff,
                               start, time.monotonic_ns()))
        return False


record_event = RecordEvent


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """``with fluid.profiler.profiler(...):`` (ref profiler.py:profiler)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def dispatch_stats() -> dict:
    """Aggregate executor dispatch counters across all live executors —
    the steady-state 'framework tax' ledger: compiled-block cache
    hits/misses, re-lowerings (``traces``), steps dispatched, host
    time-to-dispatch, and host-block time split by cause (fetch
    materialization / in-flight throttle / FLAGS_benchmark sync).  The
    per-executor view is ``Executor.dispatch_stats()``; this one sums
    them plus an ``executors`` count, so a training script can report
    dispatch overhead without holding executor references."""
    from .framework import executor as _executor
    return _executor.aggregate_dispatch_stats()


@contextlib.contextmanager
def device_profiler(logdir: str):
    """XLA/TPU device profile via jax.profiler (≈ CUPTI device tracer);
    view with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# sampling profiler: periodic jax.profiler windows for multi-day runs
# ---------------------------------------------------------------------------

class SamplingProfiler:
    """Periodic ``jax.profiler`` capture windows, driven by the executor's
    per-dispatch step counter — the multi-day-run answer to "a monolithic
    device trace of a week costs more than the training".

    Every ``every_n`` steps a window opens (``jax.profiler.start_trace``
    into its own subdirectory) and closes ``window_steps`` dispatches
    later.  Windows live in a BOUNDED rotating directory: at most
    ``max_windows`` are kept, oldest deleted first, and
    ``manifest.json`` maps every kept window to its [start, end) step
    range plus wall-clock times — so a sampled device trace correlates
    back to the step ids the executor stamps on its host spans and
    ``StepTraceAnnotation``s.

    The hot path is one attribute check when disabled
    (``every_n <= 0``); all state mutation happens at window boundaries,
    off the per-step critical path.  Capture errors never fail the step:
    they count in ``paddle_tpu_profile_windows_total{outcome="error"}``
    and disarm the window.

    **Regression auto-trigger** (``FLAGS_profile_sample_regress_frac``):
    the executor feeds its windowed-MEDIAN dispatch interval into
    ``on_step``; when the median regresses by the configured fraction
    over the best median seen, a capture window opens IMMEDIATELY —
    the trace records exactly the slow window, not whatever the
    periodic cadence happens to land on.  Hysteresis re-arms the
    trigger only after the median recovers to within half the
    threshold, so a sustained slowdown costs one window, not one per
    step.
    """

    #: medians observed before the regression baseline is trusted (the
    #: first few include compile warmup bleeding into the window)
    _REGRESS_WARMUP = 8

    def __init__(self):
        self._mu = threading.Lock()
        self.every_n = 0                 # fast-path guard (int compare)
        self.regress_frac = 0.0          # fast-path guard (float compare)
        self.window_steps = 4            # guarded-by: _mu
        self.base_dir = ""               # guarded-by: _mu
        self.max_windows = 8             # guarded-by: _mu
        self._active = None              # guarded-by: _mu  (window dict)
        self._atexit_armed = False       # guarded-by: _mu
        self._best_med = None            # guarded-by: _mu
        self._med_obs = 0                # guarded-by: _mu
        self._regress_armed = True       # guarded-by: _mu

    def configure(self, every_n: int, window_steps: int, base_dir: str,
                  max_windows: int, regress_frac: float = 0.0) -> None:
        with self._mu:
            self.window_steps = max(int(window_steps), 1)
            self.base_dir = str(base_dir) or "pt_profile_samples"
            self.max_windows = max(int(max_windows), 1)
            if not self._atexit_armed and (int(every_n) > 0 or
                                           float(regress_frac) > 0):
                import atexit
                atexit.register(self.close)
                self._atexit_armed = True
            self._best_med = None
            self._med_obs = 0
            self._regress_armed = True
            # set LAST: the armed fast path must only observe a fully
            # configured sampler
            self.regress_frac = float(regress_frac)
            self.every_n = int(every_n)

    # -- step hook (called by the executor per dispatch) ---------------------
    def on_step(self, step_id: int, step_ms=None) -> None:
        if self.every_n <= 0 and self.regress_frac <= 0 and \
                self._active is None:
            return
        with self._mu:
            act = self._active
            if act is not None:
                if step_id - act["opened_at"] >= self.window_steps:
                    # this step's annotation already closed inside the
                    # active trace: the capture runs through step_id, so
                    # the half-open manifest range ends at step_id + 1
                    self._finish_locked(act, step_id + 1)
                else:
                    act["last_step"] = step_id
                self._observe_median_locked(step_ms)
                return
            if self._observe_median_locked(step_ms):
                self._open_locked(step_id, trigger="regress")
                return
            if self.every_n > 0 and step_id % self.every_n == 0:
                self._open_locked(step_id)

    def _observe_median_locked(self, step_ms) -> bool:  # guarded-by-caller: _mu
        """Track the best median and decide whether the regression
        trigger should fire (True only when no window is active)."""
        if self.regress_frac <= 0 or step_ms is None or step_ms <= 0:
            return False
        self._med_obs += 1
        if self._best_med is None or step_ms < self._best_med:
            self._best_med = float(step_ms)
        if self._med_obs < self._REGRESS_WARMUP:
            return False
        threshold = self._best_med * (1.0 + self.regress_frac)
        if step_ms >= threshold:
            if self._regress_armed and self._active is None:
                self._regress_armed = False
                return True
            return False
        if step_ms <= self._best_med * (1.0 + self.regress_frac / 2.0):
            self._regress_armed = True    # recovered: re-arm
        return False

    def trigger_window(self, step_id=None, trigger: str = "anomaly") -> bool:
        """Open a capture window NOW (no-op while one is active) — the
        numerics anomaly engine's entry point: a NaN trip or grad-norm
        spike captures exactly the poisoned steps, stamped with
        ``trigger`` in the manifest.  Works with periodic sampling off:
        the window still closes ``window_steps`` dispatches later (the
        executor's per-dispatch hook keeps running while a window is
        active).  Returns True iff a window opened."""
        with self._mu:
            if self._active is not None:
                return False
            if not self.base_dir:
                self.base_dir = "pt_profile_samples"
            if not self._atexit_armed:
                import atexit
                atexit.register(self.close)
                self._atexit_armed = True
            self._open_locked(int(step_id or 0), trigger=trigger)
            return self._active is not None

    def close(self) -> None:
        """Finish any in-flight window (process exit / reconfigure).
        A window that observed NO steps is abandoned outright — an
        empty capture would pollute the manifest with a vacuous
        ``[N, N)`` range and burn a rotation slot."""
        import jax
        import shutil
        with self._mu:
            act = self._active
            if act is None:
                return
            if "last_step" in act:
                self._finish_locked(act, act["last_step"] + 1)
                return
            self._active = None
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                _note_window_error(e)
            _window_ctr("empty")
            shutil.rmtree(act["dir"], ignore_errors=True)

    # -- window lifecycle (all hold _mu) -------------------------------------
    def _open_locked(self, step_id: int,
                     trigger: str = "periodic"):  # guarded-by-caller: _mu
        import jax
        wdir = os.path.join(self.base_dir, f"window_{step_id:08d}")
        try:
            os.makedirs(wdir, exist_ok=True)
            jax.profiler.start_trace(wdir)
        except Exception as e:
            _window_ctr("error")
            _note_window_error(e)
            # un-manifested dirs are invisible to rotation — leaving
            # this one behind would defeat the max_windows disk bound
            # on exactly the runs (recurring capture errors) that hit
            # this path the most
            import shutil
            shutil.rmtree(wdir, ignore_errors=True)
            return
        # this hook runs at the END of step_id's dispatch — its
        # StepTraceAnnotation has already closed, so the first step the
        # open trace observes is step_id + 1 (the manifest's start)
        self._active = {"dir": wdir, "start_step": int(step_id) + 1,
                        "opened_at": int(step_id),
                        "wall_start": time.time(),
                        "trigger": trigger}
        from . import monitor as _monitor
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant("profile.window_start", "profile",
                                    {"step": int(step_id), "dir": wdir,
                                     "trigger": trigger})

    def _finish_locked(self, act, end_step: int):  # guarded-by-caller: _mu
        import jax
        self._active = None
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            _window_ctr("error")
            _note_window_error(e)
            # the partial capture never reaches the manifest, so
            # rotation could never reclaim it — delete it now (same
            # disk-bound rationale as the open-failure path)
            import shutil
            shutil.rmtree(act["dir"], ignore_errors=True)
            return
        act["end_step"] = int(end_step)
        act["wall_end"] = time.time()
        _window_ctr("ok")
        from . import monitor as _monitor
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "profile.window_stop", "profile",
                {"step": int(end_step), "dir": act["dir"]})
        try:
            self._rotate_and_manifest_locked(act)
        except OSError:
            pass          # a full disk must not fail the training step
        # post-close attribution: parse the window just captured into
        # <window>/summary.json + the measured gauges
        # (paddle_tpu_step_mfu_measured, idle fraction, per-class
        # device-time shares).  Best-effort by contract: the hook warns
        # and skips on malformed captures and must NEVER fail the step.
        try:
            from .analysis import device_profile
            device_profile.summarize_and_publish(act["dir"])
        except Exception as e:
            _note_window_error(e)

    def _rotate_and_manifest_locked(self, act):  # guarded-by-caller: _mu
        import shutil
        path = os.path.join(self.base_dir, "manifest.json")
        manifest = {"windows": []}
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            pass
        windows = [w for w in manifest.get("windows", [])
                   if isinstance(w, dict)]
        windows.append({k: act[k] for k in
                        ("dir", "start_step", "end_step",
                         "wall_start", "wall_end", "trigger")
                        if k in act})
        # dedupe by window dir, newest entry winning (a re-triggered
        # step id re-uses its dir — jax writes a fresh timestamped run
        # under plugins/profile/ — and the pre-dedupe manifest listed
        # such dirs once per capture), and prune entries whose dirs no
        # longer exist (externally deleted captures must not pin
        # rotation slots or mislead readers)
        by_dir = {}
        for w in windows:
            d = w.get("dir", "")
            prev = by_dir.get(d)
            if prev is None or w.get("wall_end", 0.0) >= \
                    prev.get("wall_end", 0.0):
                by_dir[d] = w
        windows = [w for d, w in by_dir.items()
                   if d == act.get("dir") or os.path.isdir(d)]
        windows.sort(key=lambda w: w.get("start_step", 0))
        while len(windows) > self.max_windows:
            victim = windows.pop(0)
            shutil.rmtree(victim.get("dir", ""), ignore_errors=True)
        manifest["windows"] = windows
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, path)


def _window_ctr(outcome: str):
    from . import monitor as _monitor
    _monitor.REGISTRY.counter(
        "paddle_tpu_profile_windows_total",
        "sampling-profiler capture windows by outcome",
        ("outcome",)).inc(1, outcome=outcome)


_last_window_error = []


def _note_window_error(e: BaseException):
    """Remember the last capture failure (visible via last_window_error()
    — a sampler that silently never captures is undebuggable)."""
    _last_window_error[:] = [repr(e)]


def last_window_error():
    return _last_window_error[0] if _last_window_error else None


SAMPLER = SamplingProfiler()


def maybe_sample_step(step_id: int, step_ms=None) -> None:
    """Executor per-dispatch hook: two scalar compares when sampling is
    off (the default), window open/close bookkeeping at boundaries when
    on.  ``step_ms`` is the executor's windowed-median dispatch interval
    — the signal for the regression auto-trigger."""
    SAMPLER.on_step(step_id, step_ms)
