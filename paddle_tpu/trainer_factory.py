"""TrainerFactory (ref ``python/paddle/fluid/trainer_factory.py:21``):
build a trainer descriptor + device worker pair from an optimizer's
attributes, exactly the reference's string-dispatch protocol."""

from __future__ import annotations

from .device_worker import DeviceWorker, DownpourSGD, Hogwild, Section
from .trainer_desc import (DistMultiTrainer, MultiTrainer, PipelineTrainer,
                           TrainerDesc)

__all__ = ["TrainerFactory"]

_TRAINERS = {c.__name__: c for c in
             (TrainerDesc, MultiTrainer, DistMultiTrainer, PipelineTrainer)}
_WORKERS = {c.__name__: c for c in
            (DeviceWorker, Hogwild, DownpourSGD, Section)}


class TrainerFactory:
    def _create_trainer(self, opt_info=None):
        trainer_name = "MultiTrainer"
        worker_name = "Hogwild"
        if opt_info:
            trainer_name = opt_info.get("trainer", trainer_name)
            worker_name = opt_info.get("device_worker", worker_name)
        trainer = _TRAINERS[trainer_name]()
        worker = _WORKERS[worker_name]()
        trainer.set_device_worker(worker)
        if opt_info:
            if "thread_num" in opt_info:
                trainer.set_thread(opt_info["thread_num"])
            if "fetch_var_names" in opt_info:
                trainer.set_fetch_var_and_info(
                    opt_info.get("fetch_var_names"),
                    opt_info.get("fetch_info"),
                    opt_info.get("print_period", 100))
        return trainer
