"""Elementwise-binary sugar used by Variable operator overloads and the
``elementwise_*`` layer functions (ref ``python/paddle/fluid/layers/math_op_patch.py``)."""

from __future__ import annotations

import numpy as np

from ..framework.core import Variable
from ..layer_helper import LayerHelper


def _to_variable(x, ref: Variable):
    if isinstance(x, Variable):
        return x
    helper = LayerHelper("create_scalar")
    out = helper.create_variable_for_type_inference(ref.dtype)
    out.stop_gradient = True
    val = float(x) if not isinstance(x, np.ndarray) else x
    if isinstance(val, float):
        helper.append_op("fill_constant", outputs={"Out": [out]},
                         attrs={"shape": [], "dtype": ref.dtype, "value": val})
    else:
        helper.append_op("assign_value", outputs={"Out": [out]},
                         attrs={"shape": list(val.shape), "dtype": ref.dtype,
                                "values": val.reshape(-1).tolist()})
    return out


def _elementwise_binary(x: Variable, y, op_type: str, reverse=False, axis=-1,
                        act=None, name=None):
    helper = LayerHelper(op_type, act=act, name=name)
    y = _to_variable(y, x)
    if reverse:
        x, y = y, x
    out = helper.create_variable_for_type_inference(
        x.dtype if isinstance(x, Variable) else y.dtype)
    helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)
