"""Metric layers (ref ``python/paddle/fluid/layers/metric_op.py``)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from ..param_attr import ParamAttr
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """ref metric_op.py accuracy → top_k + accuracy ops."""
    helper = LayerHelper("accuracy")
    _, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32", True)
    correct = correct or helper.create_variable_for_type_inference("int32", True)
    total = total or helper.create_variable_for_type_inference("int32", True)
    helper.append_op("accuracy",
                     inputs={"Out": [input], "Indices": [topk_indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc_out], "Correct": [correct],
                              "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """ref metric_op.py auc — streaming AUC with persistable stat buffers."""
    helper = LayerHelper("auc")
    stat_pos = helper.create_parameter(
        ParamAttr(trainable=False), shape=[num_thresholds + 1],
        dtype="float32", default_initializer=ConstantInitializer(0.0))
    stat_neg = helper.create_parameter(
        ParamAttr(trainable=False), shape=[num_thresholds + 1],
        dtype="float32", default_initializer=ConstantInitializer(0.0))
    stat_pos.stop_gradient = True
    stat_neg.stop_gradient = True
    auc_out = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("auc",
                     inputs={"Predict": [input], "Label": [label],
                             "StatPos": [stat_pos], "StatNeg": [stat_neg]},
                     outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                              "StatNegOut": [stat_neg]},
                     attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, auc_out, [stat_pos, stat_neg]
