"""``paddle_tpu.layers`` — the user-facing layer DSL (ref
``python/paddle/fluid/layers/``)."""

from . import control_flow, detection, io, learning_rate_scheduler  # noqa
from . import distributions  # noqa
from .compat import *  # noqa
from . import math_ops, metric_op, nn, sequence, tensor  # noqa
from .control_flow import (DynamicRNN, IfElse, Print, StaticRNN,  # noqa
                           Switch, While, array_length, array_read,
                           array_write, create_array, equal,
                           greater_equal, greater_than, increment,
                           is_empty, less_equal, less_than, not_equal)
from .detection import (anchor_generator, bipartite_match,  # noqa
                        box_clip, box_coder, box_decoder_and_assign,
                        collect_fpn_proposals, density_prior_box,
                        detection_output, distribute_fpn_proposals,
                        generate_mask_labels, generate_proposal_labels,
                        generate_proposals, iou_similarity,
                        multi_box_head, multiclass_nms, multiclass_nms2,
                        polygon_box_transform, prior_box, prroi_pool,
                        psroi_pool, retinanet_detection_output,
                        retinanet_target_assign, roi_align,
                        roi_perspective_transform, roi_pool,
                        rpn_target_assign, sigmoid_focal_loss, ssd_loss,
                        target_assign, yolo_box, yolov3_loss)
from .io import data  # noqa
from .learning_rate_scheduler import (cosine_decay, exponential_decay,  # noqa
                                      inverse_time_decay, linear_lr_warmup,
                                      natural_exp_decay, noam_decay,
                                      piecewise_decay, polynomial_decay)
from .math_ops import scale  # noqa
from .metric_op import accuracy, auc  # noqa
from .nn import *  # noqa
from .structured import (beam_search, beam_search_decode,  # noqa
                         crf_decoding, ctc_greedy_decoder, edit_distance,
                         hsigmoid, linear_chain_crf, nce,
                         sampled_softmax_with_cross_entropy, sampling_id,
                         warpctc)
from .sequence import sequence_conv  # noqa
from .sequence import (sequence_concat, sequence_enumerate,  # noqa
                       sequence_expand, sequence_expand_as,
                       sequence_first_step, sequence_last_step,
                       sequence_mask, sequence_pad, sequence_pool,
                       sequence_reshape, sequence_reverse,
                       sequence_slice, sequence_softmax, sequence_unpad)
from .tensor import (argmax, argmin, argsort, assign, cast, concat,  # noqa
                     create_global_var, create_parameter, create_tensor,
                     diag, eye, fill_constant,
                     fill_constant_batch_size_like, has_inf, has_nan,
                     isfinite, linspace, ones, ones_like, range, reverse,
                     sums, tensor_array_to_tensor, zeros, zeros_like)

sum = sums  # fluid exports `sum` (ref layers/nn.py __all__)
topk = nn.topk
