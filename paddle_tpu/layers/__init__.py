"""``paddle_tpu.layers`` — the user-facing layer DSL (ref
``python/paddle/fluid/layers/``)."""

from . import control_flow, detection, io, learning_rate_scheduler  # noqa
from . import math_ops, metric_op, nn, sequence, tensor  # noqa
from .control_flow import (While, equal, greater_equal, greater_than,  # noqa
                           increment, is_empty, less_equal, less_than,
                           not_equal)
from .io import data  # noqa
from .math_ops import scale  # noqa
from .metric_op import accuracy, auc  # noqa
from .nn import *  # noqa
from .sequence import (sequence_concat, sequence_expand, sequence_first_step,  # noqa
                       sequence_last_step, sequence_mask, sequence_pad,
                       sequence_pool, sequence_reverse, sequence_softmax,
                       sequence_unpad)
from .tensor import (argmax, argmin, argsort, assign, cast, concat,  # noqa
                     create_global_var, create_parameter, create_tensor,
                     diag, eye, fill_constant,
                     fill_constant_batch_size_like, has_inf, has_nan,
                     isfinite, linspace, ones, ones_like, range, reverse,
                     sums, zeros, zeros_like)
