"""Remaining Appendix-B layer wrappers (ref ``python/paddle/fluid/layers``
``__all__`` lists — SURVEY Appendix B).  Thin LayerHelper shims over
already-registered lowerings; recurrent layers create their parameters
exactly as the reference layers do."""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from ..framework.core import Variable

__all__ = [
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit", "lstm_unit",
    "lstm", "chunk_eval", "conv3d", "pool3d", "adaptive_pool3d",
    "conv3d_transpose", "lod_reset", "lod_append", "image_resize_short",
    "sequence_scatter", "affine_grid", "sequence_topk_avg_pooling",
    "continuous_value_model", "deformable_conv", "deformable_roi_pooling",
    "match_matrix_tensor", "filter_by_instag", "var_conv_2d",
    "reorder_lod_tensor_by_rank", "read_file", "double_buffer", "load",
    "py_reader", "create_py_reader_by_data",
    "atan", "tanh_shrink", "acos", "asin", "softshrink", "hard_shrink",
    "cumsum",
]


def _tuple_n(v, n):
    """int-or-sequence attr → list of n ints (the 3-D _pair)."""
    return [v] * n if isinstance(v, int) else list(v)


def _channel_bias(helper, out, num_filters, bias_attr):
    """Per-output-channel conv bias, broadcast on axis 1 (the conv2d layer
    convention)."""
    if bias_attr is False:
        return out
    b = helper.create_parameter(bias_attr, shape=[num_filters],
                                dtype=out.dtype, is_bias=True)
    pre = helper.create_variable_for_type_inference(out.dtype)
    helper.append_op("elementwise_add", inputs={"X": [out], "Y": [b]},
                     outputs={"Out": [pre]}, attrs={"axis": 1})
    return pre


def _simple(op_type, ins, outs=("Out",), attrs=None, dtype=None):
    helper = LayerHelper(op_type)
    first = next(v[0] for v in ins.values() if v)
    out_vars = [helper.create_variable_for_type_inference(
        dtype or getattr(first, "dtype", "float32")) for _ in outs]
    helper.append_op(op_type, inputs=ins,
                     outputs={o: [v] for o, v in zip(outs, out_vars)},
                     attrs=attrs or {})
    return out_vars[0] if len(out_vars) == 1 else tuple(out_vars)


# -- recurrent layers (ref layers/nn.py dynamic_lstm:*, dynamic_gru:*) -------

def dynamic_lstm(input, size, param_attr=None, bias_attr=None,
                 use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """ref layers/nn.py dynamic_lstm: input is the 4d pre-projection
    [b, t, 4d]; creates the recurrent weight + bias."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    w = helper.create_parameter(param_attr, shape=[d, 4 * d], dtype=dtype)
    bias_size = 7 * d if use_peepholes else 4 * d
    b = helper.create_parameter(bias_attr, shape=[1, bias_size],
                                dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lstm", inputs={"Input": [input], "Weight": [w], "Bias": [b]},
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """ref layers/nn.py dynamic_lstmp — LSTM with projection."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    w = helper.create_parameter(param_attr, shape=[proj_size, 4 * d],
                                dtype=dtype)
    pw = helper.create_parameter(param_attr, shape=[d, proj_size],
                                 dtype=dtype)
    bias_size = 7 * d if use_peepholes else 4 * d
    b = helper.create_parameter(bias_attr, shape=[1, bias_size],
                                dtype=dtype, is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lstmp", inputs={"Input": [input], "Weight": [w],
                         "ProjWeight": [pw], "Bias": [b]},
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                dtype="float32", name=None):
    """ref layers/nn.py dynamic_gru: input [b, t, 3d] pre-projection."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size
    w = helper.create_parameter(param_attr, shape=[d, 3 * d], dtype=dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * d], dtype=dtype,
                                is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    helper.append_op(
        "gru", inputs=ins, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation,
               "origin_mode": origin_mode})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """ref layers/nn.py gru_unit — one GRU step."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    d = size // 3
    w = helper.create_parameter(param_attr, shape=[d, 3 * d],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[1, 3 * d],
                                dtype=input.dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset_h = helper.create_variable_for_type_inference(input.dtype)
    updated = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [w],
                "Bias": [b]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                 "Hidden": [updated]},
        attrs={"activation": activation,
               "gate_activation": gate_activation,
               "origin_mode": origin_mode})
    return updated, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """ref layers/nn.py lstm_unit: fc([x, h]) then one LSTM cell step."""
    from . import nn as _nn
    from . import tensor as _tensor
    d = int(hidden_t_prev.shape[-1])
    cat = _tensor.concat([x_t, hidden_t_prev], axis=1)
    gates = _nn.fc(cat, size=4 * d, param_attr=param_attr,
                   bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         param_attr=None, bias_attr=None, dtype="float32", seed=-1):
    """ref layers/nn.py lstm (the cudnn_lstm wrapper)."""
    helper = LayerHelper("lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d_in = int(input.shape[-1])
    weight_size = 4 * hidden_size * (d_in + hidden_size + 2)
    w = helper.create_parameter(param_attr, shape=[weight_size],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cudnn_lstm",
        inputs={"Input": [input], "W": [w], "InitH": [init_h],
                "InitC": [init_c]},
        outputs={"Out": [out], "last_h": [last_h], "last_c": [last_c]},
        attrs={"hidden_size": hidden_size, "num_layers": num_layers,
               "is_bidirec": is_bidirec, "dropout_prob": dropout_prob,
               "is_test": is_test})
    return out, last_h, last_c


# -- misc nn -----------------------------------------------------------------

def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """ref layers/nn.py chunk_eval → chunk_eval op."""
    helper = LayerHelper("chunk_eval")
    outs = ["Precision", "Recall", "F1-Score", "NumInferChunks",
            "NumLabelChunks", "NumCorrectChunks"]
    out_vars = [helper.create_variable_for_type_inference("float32")
                for _ in outs]
    ins = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length]
    helper.append_op(
        "chunk_eval", inputs=ins,
        outputs={o: [v] for o, v in zip(outs, out_vars)},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": excluded_chunk_types or []})
    return tuple(out_vars)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    k = _tuple_n(filter_size, 3)
    c = int(input.shape[1])
    w = helper.create_parameter(
        param_attr, shape=[num_filters, c // groups] + list(k),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _tuple_n(stride, 3),
               "paddings": _tuple_n(padding, 3),
               "dilations": _tuple_n(dilation, 3), "groups": groups})
    pre = _channel_bias(helper, out, num_filters, bias_attr)
    return helper.append_activation(pre)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None):
    return _simple("pool3d", {"X": [input]},
                   attrs={"ksize": _tuple_n(pool_size, 3),
                          "pooling_type": pool_type,
                          "strides": _tuple_n(pool_stride, 3),
                          "paddings": _tuple_n(pool_padding, 3),
                          "global_pooling": global_pooling,
                          "ceil_mode": ceil_mode})


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    return _simple("pool3d", {"X": [input]},
                   attrs={"ksize": [pool_size] * 3
                          if isinstance(pool_size, int) else list(pool_size),
                          "pooling_type": pool_type, "strides": [1, 1, 1],
                          "paddings": [0, 0, 0], "adaptive": True,
                          "global_pooling": False})


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    st = _tuple_n(stride, 3)
    pd = _tuple_n(padding, 3)
    dl = _tuple_n(dilation, 3)
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv3d_transpose needs filter_size or output_size")
        out_sz = _tuple_n(output_size, 3)
        # k from out = (in-1)*s - 2p + d*(k-1) + 1 (ref conv2d_transpose
        # filter inference)
        k = [(out_sz[i] - (int(input.shape[2 + i]) - 1) * st[i] +
              2 * pd[i] - 1) // dl[i] + 1 for i in range(3)]
    else:
        k = _tuple_n(filter_size, 3)
    c = int(input.shape[1])
    w = helper.create_parameter(
        param_attr, shape=[c, num_filters // groups] + list(k),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": st, "paddings": pd, "dilations": dl,
               "groups": groups})
    pre = _channel_bias(helper, out, num_filters, bias_attr)
    return helper.append_activation(pre)


def lod_reset(x, y=None, target_lod=None):
    ins = {"X": [x]}
    if y is not None:
        ins["Y"] = [y]
    return _simple("lod_reset", ins,
                   attrs={"target_lod": target_lod or []})


def lod_append(x, level):
    """Dense sequences carry lengths separately — values pass through."""
    return lod_reset(x)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """ref layers/nn.py image_resize_short: scale so the short side hits
    ``out_short_len``."""
    from . import nn as _nn
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    scale = out_short_len / float(short)
    return _nn.image_resize(input,
                            out_shape=[int(round(h * scale)),
                                       int(round(w * scale))],
                            resample=resample)


def sequence_scatter(input, index, updates, name=None):
    return _simple("sequence_scatter",
                   {"X": [input], "Ids": [index], "Updates": [updates]})


def affine_grid(theta, out_shape=None, name=None):
    # the op's output slot is "Output" (affine_grid_op.cc), not "Out"
    if isinstance(out_shape, Variable):
        return _simple("affine_grid", {"Theta": [theta],
                                       "OutputShape": [out_shape]},
                       outs=("Output",))
    return _simple("affine_grid", {"Theta": [theta]}, outs=("Output",),
                   attrs={"output_shape": list(out_shape)})


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    helper = LayerHelper("sequence_topk_avg_pooling")
    out = helper.create_variable_for_type_inference(input.dtype)
    pos = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_topk_avg_pooling", inputs={"X": [input]},
                     outputs={"Out": [out], "pos": [pos]},
                     attrs={"topks": list(topks),
                            "channel_num": channel_num})
    return out


def continuous_value_model(input, cvm, use_cvm=True):
    return _simple("cvm", {"X": [input], "CVM": [cvm]},
                   outs=("Y",), attrs={"use_cvm": use_cvm})


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """ref layers/nn.py deformable_conv (v2 modulated / v1)."""
    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    k = _tuple_n(filter_size, 2)
    c = int(input.shape[1])
    w = helper.create_parameter(
        param_attr, shape=[num_filters, c // groups] + list(k),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    op_type = "deformable_conv" if modulated else "deformable_conv_v1"
    if modulated:
        ins["Mask"] = [mask]
    helper.append_op(
        op_type, inputs=ins, outputs={"Output": [out]},
        attrs={"strides": _tuple_n(stride, 2),
               "paddings": _tuple_n(padding, 2),
               "dilations": _tuple_n(dilation, 2), "groups": groups,
               "deformable_groups": deformable_groups,
               "im2col_step": im2col_step})
    return out


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    helper = LayerHelper("deformable_roi_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    top = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input], "ROIs": [rois]}
    if not no_trans:
        ins["Trans"] = [trans]
    helper.append_op(
        "deformable_psroi_pooling", inputs=ins,
        outputs={"Output": [out], "TopCount": [top]},
        attrs={"spatial_scale": spatial_scale,
               "output_dim": int(input.shape[1]) //
               (group_size[0] * group_size[1])
               if position_sensitive else int(input.shape[1]),
               "group_size": list(group_size),
               "pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "part_size": list(part_size) if part_size
               else [pooled_height, pooled_width],
               "trans_std": trans_std})
    return out


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    helper = LayerHelper("match_matrix_tensor", param_attr=param_attr,
                         act=act, name=name)
    d = int(x.shape[-1])
    w = helper.create_parameter(param_attr, shape=[d, channel_num, d],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    tmp = helper.create_variable_for_type_inference(dtype)
    helper.append_op("match_matrix_tensor",
                     inputs={"X": [x], "Y": [y], "W": [w]},
                     outputs={"Out": [out], "Tmp": [tmp]},
                     attrs={"dim_t": channel_num})
    return helper.append_activation(out), tmp


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference("float32")
    index_map = helper.create_variable_for_type_inference("int64")
    helper.append_op("filter_by_instag",
                     inputs={"Ins": [ins], "Ins_tag": [ins_tag],
                             "Filter_tag": [filter_tag]},
                     outputs={"Out": [out], "LossWeight": [loss_weight],
                              "IndexMap": [index_map]},
                     attrs={"is_lod": is_lod})
    return out, loss_weight


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32",
                name=None):
    helper = LayerHelper("var_conv_2d", param_attr=param_attr, act=act,
                         name=name)
    k = _tuple_n(filter_size, 2)
    w = helper.create_parameter(
        param_attr, shape=[output_channel, input_channel] + list(k),
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("var_conv_2d", inputs={"X": [input], "W": [w]},
                     outputs={"Out": [out]},
                     attrs={"strides": _tuple_n(stride, 2),
                            "paddings": [k[0] // 2, k[1] // 2]})
    return helper.append_activation(out)


def reorder_lod_tensor_by_rank(x, rank_table):
    return _simple("reorder_lod_tensor_by_rank",
                   {"X": [x], "RankTable": [rank_table]})


# -- io shims (ref layers/io.py) ---------------------------------------------

def read_file(reader):
    """Dense pipelines read through DataLoader/PyReader; pass-through."""
    return reader


def double_buffer(reader, place=None, name=None):
    """Device prefetch is the DataLoader's job under XLA; pass-through."""
    return reader


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """ref layers/io.py py_reader: creates data vars for the given
    shapes/dtypes and binds a PyReader to them."""
    from ..data.py_reader import PyReader
    from . import io as _io
    feed_list = [
        _io.data(f"{name or 'py_reader'}_in_{i}",
                 shape=list(shape)[1:], dtype=dtype)
        for i, (shape, dtype) in enumerate(zip(shapes, dtypes))]
    return PyReader(feed_list=feed_list, capacity=capacity,
                    use_double_buffer=use_double_buffer)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..data.py_reader import PyReader
    return PyReader(feed_list=feed_list, capacity=capacity,
                    use_double_buffer=use_double_buffer)


def load(out, file_path, load_as_fp16=False):
    """ref layers/io.py load → host-side value load into the scope var."""
    from ..framework.scope import global_scope
    arr = np.load(file_path) if file_path.endswith(".npy") else \
        np.fromfile(file_path, dtype="float32")
    if load_as_fp16:
        arr = arr.astype(np.float16)
    global_scope().set_var(out.name if hasattr(out, "name") else out, arr)
    return out


# -- autogen-style unary activations (ref layers/ops.py) ---------------------

def _unary(op_type):
    def f(x, name=None):
        return _simple(op_type, {"X": [x]})
    f.__name__ = op_type
    f.__doc__ = f"ref layers/ops.py {op_type} (autogen from OpProto)."
    return f


atan = _unary("atan")
tanh_shrink = _unary("tanh_shrink")
acos = _unary("acos")
asin = _unary("asin")


def softshrink(x, alpha=0.5, name=None):
    return _simple("softshrink", {"X": [x]}, attrs={"lambda": alpha})


def hard_shrink(x, threshold=0.5, name=None):
    return _simple("hard_shrink", {"X": [x]}, attrs={"threshold": threshold})


def cumsum(x, axis=None, exclusive=False, reverse=False, name=None):
    return _simple("cumsum", {"X": [x]},
                   attrs={"axis": -1 if axis is None else axis,
                          "flatten": axis is None, "exclusive": exclusive,
                          "reverse": reverse})
