"""Structured-prediction layers: CRF, CTC, beam search, candidate sampling.

ref ``python/paddle/fluid/layers/nn.py`` (linear_chain_crf, crf_decoding,
ctc_greedy_decoder, edit_distance, warpctc, nce, hsigmoid,
sampled_softmax_with_cross_entropy, sampling_id, beam_search) — signatures
follow the reference; sequence data is dense padded + explicit lengths
instead of LoD.
"""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper


def linear_chain_crf(input, label, param_attr=None, length=None):
    """ref layers/nn.py linear_chain_crf → linear_chain_crf op.

    Returns the per-sequence negative log-likelihood ``[batch, 1]`` (minimize
    its mean).  ``input``: emissions ``[batch, time, n_tags]``; ``label``:
    ``[batch, time]``; ``length``: ``[batch]`` valid lengths.
    """
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    n_tags = input.shape[-1]
    transition = helper.create_parameter(param_attr, shape=[n_tags + 2, n_tags],
                                         dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    em_exps = helper.create_variable_for_type_inference(input.dtype)
    tr_exps = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Emission": [input], "Transition": [transition], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("linear_chain_crf", inputs=ins,
                     outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                              "EmissionExps": [em_exps],
                              "TransitionExps": [tr_exps]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """ref layers/nn.py crf_decoding → crf_decoding op (Viterbi).

    Pass the SAME ``param_attr`` (by name) as the ``linear_chain_crf`` layer
    to decode with the learned transitions.
    """
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    n_tags = input.shape[-1]
    transition = helper.create_parameter(param_attr, shape=[n_tags + 2, n_tags],
                                         dtype=input.dtype)
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [path]})
    return path


def ctc_greedy_decoder(input, blank, input_length=None):
    """ref layers/nn.py ctc_greedy_decoder: argmax per step, merge repeats,
    drop blanks.  Returns (decoded ``[batch, time]`` padded with 0,
    out_length ``[batch, 1]``)."""
    from .tensor import argmax
    helper = LayerHelper("ctc_greedy_decoder")
    ids = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int64")
    ins = {"Input": [ids]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    helper.append_op("ctc_align", inputs=ins,
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": blank, "merge_repeated": True,
                            "padding_value": 0})
    return out, out_len


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """ref layers/nn.py edit_distance → edit_distance op (Levenshtein)."""
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op("edit_distance", inputs=ins,
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """ref layers/nn.py warpctc → warpctc op (CTC loss).

    ``input``: logits ``[batch, time, num_classes]`` (pre-softmax);
    ``label``: ``[batch, max_label_len]``.  Returns loss ``[batch, 1]``.
    """
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op("warpctc", inputs=ins,
                     outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """ref layers/nn.py nce → nce op (noise-contrastive estimation)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_total_classes, 1],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    s_logits = helper.create_variable_for_type_inference(input.dtype)
    s_labels = helper.create_variable_for_type_inference("int64")
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("nce", inputs=ins,
                     outputs={"Cost": [cost], "SampleLogits": [s_logits],
                              "SampleLabels": [s_labels]},
                     attrs={"num_total_classes": num_total_classes,
                            "num_neg_samples": num_neg_samples or 10,
                            "sampler": {"uniform": 0, "log_uniform": 1}.get(
                                sampler, 0),
                            "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """ref layers/nn.py hsigmoid → hierarchical_sigmoid op over the default
    complete binary tree (ref operators/math/matrix_bit_code.h SimpleCode)."""
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_classes - 1, 1],
                                dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op("hierarchical_sigmoid", inputs=ins,
                     outputs={"Out": [out], "PreOut": [pre_out]},
                     attrs={"num_classes": num_classes})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """ref layers/nn.py sampled_softmax_with_cross_entropy → sample_logits +
    softmax_with_cross_entropy over the sampled subset."""
    helper = LayerHelper("sample_logits")
    samples = helper.create_variable_for_type_inference("int64")
    probs = helper.create_variable_for_type_inference(logits.dtype)
    s_logits = helper.create_variable_for_type_inference(logits.dtype)
    s_labels = helper.create_variable_for_type_inference("int64")
    helper.append_op("sample_logits",
                     inputs={"Logits": [logits], "Labels": [label]},
                     outputs={"Samples": [samples], "Probabilities": [probs],
                              "SampledLogits": [s_logits],
                              "SampledLabels": [s_labels]},
                     attrs={"num_samples": num_samples, "seed": seed})
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [s_logits], "Label": [s_labels]},
                     outputs={"Loss": [loss], "Softmax": [softmax]},
                     attrs={"soft_label": False, "axis": -1})
    return loss


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """ref layers/nn.py sampling_id → sampling_id op: sample one class index
    per row of the probability matrix."""
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"seed": seed})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=True):
    """ref layers/nn.py beam_search → beam_search op (one decode step).

    Dense layout: rows are ``batch*beam_size`` hypothesis slots.  Seed step 0
    with ``pre_scores`` 0 for beam 0 and a large negative for the rest.
    Returns (selected_ids, selected_scores, parent_idx).
    """
    helper = LayerHelper("beam_search")
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference(
        pre_scores.dtype)
    parent = helper.create_variable_for_type_inference("int64")
    ins = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
           "scores": [scores]}
    if ids is not None:
        ins["ids"] = [ids]
    helper.append_op("beam_search", inputs=ins,
                     outputs={"selected_ids": [sel_ids],
                              "selected_scores": [sel_scores],
                              "parent_idx": [parent]},
                     attrs={"beam_size": beam_size, "end_id": end_id,
                            "level": level, "is_accumulated": is_accumulated})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, parents, beam_size, end_id, name=None):
    """ref layers/nn.py beam_search_decode → beam_search_decode op.

    ``ids``/``scores``/``parents`` are stacked step tensors ``[time,
    batch*beam(,1)]`` (e.g. ``tensor_array_to_tensor`` of the per-step
    outputs of :func:`beam_search`).  The reference recovers parent pointers
    from LoD; the dense layout passes them explicitly.  Returns
    (sentence_ids ``[batch, beam, time]``, sentence_scores).
    """
    helper = LayerHelper("beam_search_decode")
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op("beam_search_decode",
                     inputs={"Ids": [ids], "Scores": [scores],
                             "Parents": [parents]},
                     outputs={"SentenceIds": [sent_ids],
                              "SentenceScores": [sent_scores]},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    return sent_ids, sent_scores
