"""Probability distributions (ref ``python/paddle/fluid/layers/
distributions.py``): Uniform, Normal, Categorical, MultivariateNormalDiag
built from the layer DSL, so every method returns graph Variables."""

from __future__ import annotations

import math

import numpy as np

from ..framework.core import Variable
from . import nn, tensor

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _to_variable(x, name="dist_const"):
    if isinstance(x, Variable):
        return x
    arr = np.asarray(x, np.float32)
    return tensor.assign(arr if arr.ndim else arr.reshape(1))


class Distribution:
    """ref distributions.py Distribution base."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) (ref distributions.py Uniform)."""

    def __init__(self, low, high):
        self.low = _to_variable(low)
        self.high = _to_variable(high)

    def sample(self, shape, seed=0):
        u = nn.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return self.low + u * (self.high - self.low)

    def entropy(self):
        return nn.log(self.high - self.low)

    def log_prob(self, value):
        # in-support density 1/(high-low); the reference likewise does not
        # mask out-of-support values
        return 0.0 - nn.log(self.high - self.low) + \
            tensor.zeros_like(value)


class Normal(Distribution):
    """N(loc, scale) (ref distributions.py Normal)."""

    def __init__(self, loc, scale):
        self.loc = _to_variable(loc)
        self.scale = _to_variable(scale)

    def sample(self, shape, seed=0):
        z = nn.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return self.loc + z * self.scale

    def entropy(self):
        half_log_2pi_e = 0.5 + 0.5 * math.log(2.0 * math.pi)
        return half_log_2pi_e + nn.log(self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        log_scale = nn.log(self.scale)
        diff = value - self.loc
        return 0.0 - (diff * diff) / (2.0 * var) - log_scale \
            - 0.5 * math.log(2.0 * math.pi)

    def kl_divergence(self, other):
        """KL(self ‖ other) for two Normals (ref :kl_divergence)."""
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - nn.log(var_ratio))


class Categorical(Distribution):
    """Categorical over unnormalized logits (ref distributions.py
    Categorical: entropy + kl_divergence)."""

    def __init__(self, logits):
        self.logits = _to_variable(logits)

    def _probs_and_logp(self, logits):
        z = logits - nn.reduce_max(logits, dim=[-1], keep_dim=True)
        e = nn.exp(z)
        denom = nn.reduce_sum(e, dim=[-1], keep_dim=True)
        prob = e / denom
        logp = z - nn.log(denom)
        return prob, logp

    def entropy(self):
        prob, logp = self._probs_and_logp(self.logits)
        return 0.0 - nn.reduce_sum(prob * logp, dim=[-1])

    def kl_divergence(self, other):
        prob, logp = self._probs_and_logp(self.logits)
        _, logq = self._probs_and_logp(other.logits)
        return nn.reduce_sum(prob * (logp - logq), dim=[-1])


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)) — diagonal-covariance multivariate normal
    (ref distributions.py MultivariateNormalDiag: entropy + kl)."""

    def __init__(self, loc, scale):
        # scale is the DIAGONAL MATRIX [k,k] in the reference's API
        self.loc = _to_variable(loc)
        self.scale = _to_variable(scale)

    def _diag(self):
        k = self.scale.shape[-1]
        eye = tensor.assign(np.eye(k, dtype=np.float32))
        return nn.reduce_sum(self.scale * eye, dim=[-1])

    def entropy(self):
        d = self._diag()
        k = float(d.shape[-1])
        log_det = nn.reduce_sum(nn.log(d), dim=[-1])
        return 0.5 * k * (1.0 + math.log(2.0 * math.pi)) + log_det

    def kl_divergence(self, other):
        d1, d2 = self._diag(), other._diag()
        var1, var2 = d1 * d1, d2 * d2
        diff = self.loc - other.loc
        k = float(d1.shape[-1])
        tr = nn.reduce_sum(var1 / var2, dim=[-1])
        quad = nn.reduce_sum(diff * diff / var2, dim=[-1])
        log_det = nn.reduce_sum(nn.log(var2) - nn.log(var1),
                                dim=[-1])
        return 0.5 * (tr + quad - k + log_det)
