"""Detection layers (ref ``python/paddle/fluid/layers/detection.py`` — the
27-export surface).

Dense fixed-shape semantics throughout: NMS-style layers return
``[batch, K, ...]`` padded buffers + counts instead of LoD (see
``ops/detection_ops.py``).  Ragged gt inputs are padded ``[batch, G, ...]``
with zero-area rows ignored.
"""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = [
    "prior_box", "density_prior_box", "multi_box_head", "bipartite_match",
    "target_assign", "detection_output", "ssd_loss", "rpn_target_assign",
    "retinanet_target_assign", "sigmoid_focal_loss", "anchor_generator",
    "roi_perspective_transform", "generate_proposal_labels",
    "generate_proposals", "generate_mask_labels", "iou_similarity",
    "box_coder", "polygon_box_transform", "yolov3_loss", "yolo_box",
    "box_clip", "multiclass_nms", "multiclass_nms2",
    "retinanet_detection_output", "distribute_fpn_proposals",
    "box_decoder_and_assign", "collect_fpn_proposals",
    "roi_pool", "roi_align", "psroi_pool", "prroi_pool",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """ref layers/detection.py prior_box → prior_box op."""
    helper = LayerHelper("prior_box", name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("prior_box",
                     inputs={"Input": [input], "Image": [image]},
                     outputs={"Boxes": [box], "Variances": [var]},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance), "flip": flip,
                            "clip": clip, "step_w": steps[0],
                            "step_h": steps[1], "offset": offset,
                            "min_max_aspect_ratios_order":
                                min_max_aspect_ratios_order})
    return box, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    """ref layers/detection.py density_prior_box → density_prior_box op."""
    helper = LayerHelper("density_prior_box", name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("density_prior_box",
                     inputs={"Input": [input], "Image": [image]},
                     outputs={"Boxes": [box], "Variances": [var]},
                     attrs={"densities": list(densities or []),
                            "fixed_sizes": list(fixed_sizes or []),
                            "fixed_ratios": list(fixed_ratios or []),
                            "variances": list(variance), "clip": clip,
                            "step_w": steps[0], "step_h": steps[1],
                            "offset": offset,
                            "flatten_to_2d": flatten_to_2d})
    if flatten_to_2d:
        from . import nn
        box = nn.reshape(box, [-1, 4])
        var = nn.reshape(var, [-1, 4])
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    """ref layers/detection.py anchor_generator → anchor_generator op."""
    helper = LayerHelper("anchor_generator", name=name)
    anchor = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("anchor_generator", inputs={"Input": [input]},
                     outputs={"Anchors": [anchor], "Variances": [var]},
                     attrs={"anchor_sizes": list(anchor_sizes or
                                                 [64., 128., 256., 512.]),
                            "aspect_ratios": list(aspect_ratios or
                                                  [0.5, 1.0, 2.0]),
                            "variances": list(variance),
                            "stride": list(stride or [16.0, 16.0]),
                            "offset": offset})
    return anchor, var


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            attrs["variance"] = [float(v) for v in prior_box_var]
        else:
            inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]}, attrs=attrs)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """ref layers/detection.py bipartite_match → bipartite_match op."""
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference("int32", True)
    match_dist = helper.create_variable_for_type_inference(
        dist_matrix.dtype, True)
    helper.append_op("bipartite_match",
                     inputs={"DistMat": [dist_matrix]},
                     outputs={"ColToRowMatchIndices": [match_indices],
                              "ColToRowMatchDist": [match_dist]},
                     attrs={"match_type": "bipartite" if match_type is None
                            else match_type,
                            "dist_threshold": 0.5 if dist_threshold is None
                            else dist_threshold})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """ref layers/detection.py target_assign → target_assign op."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    helper.append_op("target_assign",
                     inputs={"X": [input],
                             "MatchIndices": [matched_indices]},
                     outputs={"Out": [out], "OutWeight": [out_weight]},
                     attrs={"mismatch_value": mismatch_value or 0})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """ref layers/detection.py multiclass_nms → dense Out [b, keep_top_k, 6]
    (label, score, x1, y1, x2, y2) padded with -1."""
    return multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                           keep_top_k, nms_threshold, normalized, nms_eta,
                           background_label, return_index=False, name=name)


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """ref multiclass_nms2: same as multiclass_nms, optionally also the
    selected indices."""
    helper = LayerHelper("multiclass_nms2", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    num = helper.create_variable_for_type_inference("int32")
    index = helper.create_variable_for_type_inference("int64")
    helper.append_op("multiclass_nms",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out], "NmsRoisNum": [num],
                              "Index": [index]},
                     attrs={"background_label": background_label,
                            "score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "nms_threshold": nms_threshold,
                            "nms_eta": nms_eta, "keep_top_k": keep_top_k,
                            "normalized": normalized})
    if return_index:
        return out, index
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """ref layers/detection.py detection_output → detection_output op
    (decode + multiclass NMS)."""
    helper = LayerHelper("detection_output", name=name)
    out = helper.create_variable_for_type_inference(loc.dtype)
    num = helper.create_variable_for_type_inference("int32")
    index = helper.create_variable_for_type_inference("int64")
    helper.append_op("detection_output",
                     inputs={"Loc": [loc], "Scores": [scores],
                             "PriorBox": [prior_box],
                             "PriorBoxVar": [prior_box_var]},
                     outputs={"Out": [out], "NmsRoisNum": [num],
                              "Index": [index]},
                     attrs={"background_label": background_label,
                            "nms_threshold": nms_threshold,
                            "nms_top_k": nms_top_k,
                            "keep_top_k": keep_top_k,
                            "score_threshold": score_threshold,
                            "nms_eta": nms_eta})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """ref layers/detection.py ssd_loss (match → target-assign → mined conf
    CE + positive loc smooth_l1).  The reference composes ~10 ops; here the
    whole pipeline is ONE fused differentiable lowering (XLA fuses it
    anyway, and the matching/mining indices are non-differentiable
    bookkeeping).  gt inputs are padded dense ``[b, G, ...]``; zero-area gt
    rows are ignored by the matcher.  Returns per-prior weighted loss
    ``[b, M, 1]``."""
    helper = LayerHelper("ssd_loss")
    out = helper.create_variable_for_type_inference(location.dtype)
    ins = {"Location": [location], "Confidence": [confidence],
           "GtBox": [gt_box], "GtLabel": [gt_label],
           "PriorBox": [prior_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    helper.append_op("ssd_loss", inputs=ins, outputs={"Out": [out]},
                     attrs={"background_label": background_label,
                            "overlap_threshold": overlap_threshold,
                            "neg_pos_ratio": neg_pos_ratio,
                            "neg_overlap": neg_overlap,
                            "loc_loss_weight": loc_loss_weight,
                            "conf_loss_weight": conf_loss_weight,
                            "match_type": match_type,
                            "mining_type": mining_type,
                            "normalize": normalize})
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """ref layers/detection.py rpn_target_assign → rpn_target_assign op.

    Dense variant: returns (pred_scores, pred_loc, tgt_label, tgt_bbox,
    bbox_inside_weight) as full per-anchor tensors; rows with label -1 are
    ignore (mask them in the loss instead of gathering a dynamic subset).
    """
    helper = LayerHelper("rpn_target_assign")
    from . import nn
    anchor_flat = nn.reshape(anchor_box, [-1, 4])
    labels = helper.create_variable_for_type_inference("int64")
    match = helper.create_variable_for_type_inference("int32")
    tgt = helper.create_variable_for_type_inference("float32")
    score_idx = helper.create_variable_for_type_inference("int32")
    inw = helper.create_variable_for_type_inference("float32")
    helper.append_op("rpn_target_assign",
                     inputs={"Anchor": [anchor_flat],
                             "GtBoxes": [gt_boxes]},
                     outputs={"TargetLabel": [labels],
                              "LocationIndex": [match],
                              "ScoreIndex": [score_idx],
                              "TargetBBox": [tgt],
                              "BBoxInsideWeight": [inw]},
                     attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
                            "rpn_fg_fraction": rpn_fg_fraction,
                            "rpn_positive_overlap": rpn_positive_overlap,
                            "rpn_negative_overlap": rpn_negative_overlap})
    return cls_logits, bbox_pred, labels, tgt, inw


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """ref layers/detection.py retinanet_target_assign."""
    helper = LayerHelper("retinanet_target_assign")
    from . import nn
    anchor_flat = nn.reshape(anchor_box, [-1, 4])
    labels = helper.create_variable_for_type_inference("int64")
    tgt = helper.create_variable_for_type_inference("float32")
    fg_num = helper.create_variable_for_type_inference("int32")
    inw = helper.create_variable_for_type_inference("float32")
    helper.append_op("retinanet_target_assign",
                     inputs={"Anchor": [anchor_flat],
                             "GtBoxes": [gt_boxes],
                             "GtLabels": [gt_labels]},
                     outputs={"TargetLabel": [labels], "TargetBBox": [tgt],
                              "ForegroundNumber": [fg_num],
                              "BBoxInsideWeight": [inw]},
                     attrs={"positive_overlap": positive_overlap,
                            "negative_overlap": negative_overlap})
    return cls_logits, bbox_pred, labels, tgt, inw, fg_num


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """ref layers/detection.py sigmoid_focal_loss → sigmoid_focal_loss op."""
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
                     outputs={"Out": [out]},
                     attrs={"gamma": gamma, "alpha": alpha})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype, True)
    scores = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
                     outputs={"Boxes": [boxes], "Scores": [scores]},
                     attrs={"anchors": list(anchors), "class_num": class_num,
                            "conf_thresh": conf_thresh,
                            "downsample_ratio": downsample_ratio,
                            "clip_bbox": clip_bbox})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """ref layers/detection.py yolov3_loss → yolov3_loss op."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(x.dtype)
    gt_match = helper.create_variable_for_type_inference("int32")
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score]
    helper.append_op("yolov3_loss", inputs=ins,
                     outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                              "GTMatchMask": [gt_match]},
                     attrs={"anchors": list(anchors),
                            "anchor_mask": list(anchor_mask),
                            "class_num": class_num,
                            "ignore_thresh": ignore_thresh,
                            "downsample_ratio": downsample_ratio,
                            "use_label_smooth": use_label_smooth})
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """ref layers/detection.py multi_box_head: per-feature-map conv heads +
    prior boxes, concatenated over maps (the SSD head)."""
    from . import nn, tensor
    n_layer = len(inputs)
    if min_sizes is None:
        # ref: interpolate ratios between min_ratio and max_ratio
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[0],
                                            (list, tuple)) else aspect_ratios
        st = steps[i] if steps else [
            step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0]
        box, var = prior_box(inp, image,
                             [mins] if not isinstance(mins, list) else mins,
                             [maxs] if maxs and not isinstance(maxs, list)
                             else maxs,
                             ar, variance, flip, clip, st, offset,
                             min_max_aspect_ratios_order=
                             min_max_aspect_ratios_order)
        num_boxes = int(np.prod(box.shape[:-1]))
        n_per_cell = box.shape[2]
        boxes_l.append(nn.reshape(box, [-1, 4]))
        vars_l.append(nn.reshape(var, [-1, 4]))
        loc = nn.conv2d(inp, n_per_cell * 4, kernel_size, padding=pad,
                        stride=stride)
        # [b, p4, h, w] -> [b, h, w, p4] -> [b, -1, 4]
        loc = nn.transpose(loc, [0, 2, 3, 1])
        locs.append(nn.reshape(loc, [0, -1, 4]))
        conf = nn.conv2d(inp, n_per_cell * num_classes, kernel_size,
                         padding=pad, stride=stride)
        conf = nn.transpose(conf, [0, 2, 3, 1])
        confs.append(nn.reshape(conf, [0, -1, num_classes]))

    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    box = tensor.concat(boxes_l, axis=0)
    var = tensor.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, box, var


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """roi_pool op; ``rois`` dense [n, 4]; ``rois_num`` per-image ROI
    counts [b] (the reference's RoisNum/LoD convention)."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int64")
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op("roi_pool", inputs=ins,
                     outputs={"Out": [out], "Argmax": [argmax]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op("roi_align", inputs=ins, outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale,
                            "sampling_ratio": sampling_ratio})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op("psroi_pool", inputs=ins, outputs={"Out": [out]},
                     attrs={"output_channels": output_channels,
                            "spatial_scale": spatial_scale,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def prroi_pool(input, rois, output_channels=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, rois_num=None, name=None):
    helper = LayerHelper("prroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op("prroi_pool", inputs=ins, outputs={"Out": [out]},
                     attrs={"spatial_scale": spatial_scale,
                            "pooled_height": pooled_height,
                            "pooled_width": pooled_width})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    o2i = helper.create_variable_for_type_inference("int64")
    o2w = helper.create_variable_for_type_inference("float32")
    tm = helper.create_variable_for_type_inference("float32")
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op("roi_perspective_transform", inputs=ins,
                     outputs={"Out": [out], "Out2InIdx": [o2i],
                              "Out2InWeights": [o2w],
                              "TransformMatrix": [tm]},
                     attrs={"transformed_height": transformed_height,
                            "transformed_width": transformed_width,
                            "spatial_scale": spatial_scale})
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    """ref layers/detection.py generate_proposals → generate_proposals op.
    Dense: RpnRois [b, post_nms_top_n, 4] zero-padded + RpnRoisNum [b]."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("generate_proposals",
                     inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                             "ImInfo": [im_info], "Anchors": [anchors],
                             "Variances": [variances]},
                     outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                              "RpnRoisNum": [num]},
                     attrs={"pre_nms_topN": pre_nms_top_n,
                            "post_nms_topN": post_nms_top_n,
                            "nms_thresh": nms_thresh, "min_size": min_size,
                            "eta": eta})
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """ref layers/detection.py generate_proposal_labels op."""
    helper = LayerHelper("generate_proposal_labels")
    rois = helper.create_variable_for_type_inference("float32")
    labels = helper.create_variable_for_type_inference("int64")
    tgt = helper.create_variable_for_type_inference("float32")
    inw = helper.create_variable_for_type_inference("float32")
    outw = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("generate_proposal_labels",
                     inputs={"RpnRois": [rpn_rois],
                             "GtClasses": [gt_classes],
                             "GtBoxes": [gt_boxes]},
                     outputs={"Rois": [rois], "LabelsInt32": [labels],
                              "BboxTargets": [tgt],
                              "BboxInsideWeights": [inw],
                              "BboxOutsideWeights": [outw],
                              "RoisNum": [num]},
                     attrs={"batch_size_per_im": batch_size_per_im,
                            "fg_fraction": fg_fraction,
                            "fg_thresh": fg_thresh,
                            "bg_thresh_hi": bg_thresh_hi,
                            "bg_thresh_lo": bg_thresh_lo,
                            "bbox_reg_weights": list(bbox_reg_weights),
                            "class_nums": class_nums or 81})
    return rois, labels, tgt, inw, outw


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         match_indices=None):
    """ref layers/detection.py generate_mask_labels op (box-approx segms)."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = helper.create_variable_for_type_inference("float32")
    has_mask = helper.create_variable_for_type_inference("int32")
    mask_int32 = helper.create_variable_for_type_inference("int32")
    ins = {"Rois": [rois], "LabelsInt32": [labels_int32],
           "GtSegms": [gt_segms]}
    if match_indices is not None:
        ins["MatchIndices"] = [match_indices]
    helper.append_op("generate_mask_labels", inputs=ins,
                     outputs={"MaskRois": [mask_rois],
                              "RoiHasMaskInt32": [has_mask],
                              "MaskInt32": [mask_int32]},
                     attrs={"num_classes": num_classes,
                            "resolution": resolution})
    return mask_rois, has_mask, mask_int32


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """ref layers/detection.py distribute_fpn_proposals op.  Dense: each
    level's buffer is [n, 4] with non-member rows zeroed; masks say which."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_level = max_level - min_level + 1
    outs = [helper.create_variable_for_type_inference("float32")
            for _ in range(n_level)]
    masks = [helper.create_variable_for_type_inference("int32")
             for _ in range(n_level)]
    restore = helper.create_variable_for_type_inference("int32")
    helper.append_op("distribute_fpn_proposals",
                     inputs={"FpnRois": [fpn_rois]},
                     outputs={"MultiFpnRois": outs,
                              "MultiLevelMask": masks,
                              "RestoreIndex": [restore]},
                     attrs={"min_level": min_level, "max_level": max_level,
                            "refer_level": refer_level,
                            "refer_scale": refer_scale})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """ref layers/detection.py collect_fpn_proposals op."""
    helper = LayerHelper("collect_fpn_proposals", name=name)
    out = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("collect_fpn_proposals",
                     inputs={"MultiLevelRois": list(multi_rois),
                             "MultiLevelScores": list(multi_scores)},
                     outputs={"FpnRois": [out], "RoisNum": [num]},
                     attrs={"post_nms_topN": post_nms_top_n})
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip_value=4.135, name=None):
    """ref layers/detection.py box_decoder_and_assign op."""
    helper = LayerHelper("box_decoder_and_assign", name=name)
    decoded = helper.create_variable_for_type_inference(target_box.dtype)
    assigned = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op("box_decoder_and_assign",
                     inputs={"PriorBox": [prior_box],
                             "PriorBoxVar": [prior_box_var],
                             "TargetBox": [target_box],
                             "BoxScore": [box_score]},
                     outputs={"DecodeBox": [decoded],
                              "OutputAssignBox": [assigned]},
                     attrs={"box_clip": box_clip_value})
    return decoded, assigned


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """ref layers/detection.py retinanet_detection_output op.

    ``bboxes``: per-level delta tensors [b, Ai, 4]; ``scores``: per-level
    sigmoid scores [b, Ai, C]; ``anchors``: per-level anchors [Ai, 4].
    """
    helper = LayerHelper("retinanet_detection_output")
    out = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    index = helper.create_variable_for_type_inference("int64")
    helper.append_op("retinanet_detection_output",
                     inputs={"BBoxes": list(anchors),
                             "Deltas": list(bboxes),
                             "Scores": list(scores),
                             "ImInfo": [im_info]},
                     outputs={"Out": [out], "NmsRoisNum": [num],
                              "Index": [index]},
                     attrs={"score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "keep_top_k": keep_top_k,
                            "nms_threshold": nms_threshold,
                            "nms_eta": nms_eta})
    return out
