"""Detection layers (ref ``python/paddle/fluid/layers/detection.py`` — 27
exports).  Round 1 ships the box/anchor math subset; NMS-style ops that are
host-side in every framework surface as NotImplemented with guidance."""

from __future__ import annotations

from ..layer_helper import LayerHelper


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    box = helper.create_variable_for_type_inference(input.dtype, True)
    var = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("prior_box",
                     inputs={"Input": [input], "Image": [image]},
                     outputs={"Boxes": [box], "Variances": [var]},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance), "flip": flip,
                            "clip": clip, "step_w": steps[0],
                            "step_h": steps[1], "offset": offset,
                            "min_max_aspect_ratios_order":
                                min_max_aspect_ratios_order})
    return box, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized, "axis": axis})
    return out


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("box_clip", inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype, True)
    scores = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
                     outputs={"Boxes": [boxes], "Scores": [scores]},
                     attrs={"anchors": list(anchors), "class_num": class_num,
                            "conf_thresh": conf_thresh,
                            "downsample_ratio": downsample_ratio})
    return boxes, scores


def multiclass_nms(*a, **k):
    raise NotImplementedError(
        "multiclass_nms: dynamic-output NMS is host-side; run it on fetched "
        "numpy outputs via paddle_tpu.utils.nms.multiclass_nms_np")


def detection_output(*a, **k):
    raise NotImplementedError("detection_output: see multiclass_nms")
