"""Control-flow layers (ref ``python/paddle/fluid/layers/control_flow.py``).

Comparison helpers plus ``increment``/``array`` utilities.  Structured loops
(While/StaticRNN/DynamicRNN) lower to ``lax.while_loop``/``lax.scan`` — see
``paddle_tpu.ops.control_flow_ops``.  Note the TPU-semantics difference the
reference doesn't have: loop bodies are traced once and must be
shape-static; reverse-mode grads flow through ``StaticRNN``/``DynamicRNN``
(scan) but not ``While`` (while_loop), matching JAX.
"""

from __future__ import annotations

from ..framework import unique_name
from ..framework.core import Variable, default_main_program
from ..layer_helper import LayerHelper
from . import tensor


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


class While:
    """``while cond: body`` over a sub-block.

    ref control_flow.py While / operators/controlflow/while_op.cc:43.

    Two lowerings:
    - unbounded (default): ``lax.while_loop`` — forward-only
      (``while_loop`` has no reverse-mode rule);
    - ``max_trip_count=N``: a ``lax.scan`` over N steps with an
      active-mask (iterations after the condition turns false pass the
      carry through unchanged), which IS reverse-differentiable — the
      TPU analog of the reference's ``WhileGradOp``
      (operators/controlflow/while_op.cc:312).  The loop must converge
      within N trips; extra trips cost compute but not correctness.
    """

    def __init__(self, cond, is_test=False, name=None,
                 max_trip_count=None):
        self.cond_var = cond
        self.program = default_main_program()
        self.helper = LayerHelper("while", name=name)
        self.max_trip_count = max_trip_count

    def block(self):
        return _WhileBlockGuard(self)


def _collect_io(block):
    """(reads, writes) over a block INCLUDING nested sub-blocks — a Switch
    inside a While reads/writes through a conditional_block whose body the
    outer capture analysis must see."""
    reads, writes = set(), set()
    for op in block.ops:
        reads.update(op.input_arg_names())
        writes.update(op.output_arg_names())
        for val in op.attrs.values():
            if hasattr(val, "ops") and hasattr(val, "vars"):   # a Block
                r, w = _collect_io(val)
                reads.update(r)
                writes.update(w)
    return reads, writes


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.while_op = while_op

    def __enter__(self):
        self.block = self.while_op.program._create_block()
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        program = self.while_op.program
        inner = program.current_block()
        program._rollback()
        parent = program.current_block()
        # loop-carried vars: every var read in the sub-block that lives in the
        # parent and is written in the sub-block, plus the condition var;
        # collection recurses into nested conditional sub-blocks
        read, written = _collect_io(inner)
        # membership must be recursive (has_var) — parent.vars is local-only,
        # and the loop may sit inside another sub-block whose captures live
        # further up the chain
        carried = sorted({n for n in (read | written) if parent.has_var(n)}
                         | {self.while_op.cond_var.name})
        reads = sorted(n for n in read if parent.has_var(n))
        max_trips = self.while_op.max_trip_count
        inputs = {"Condition": [self.while_op.cond_var.name], "X": reads}
        attrs = {"sub_block": inner, "carried_vars": list(carried),
                 "cond_var": self.while_op.cond_var.name}
        if max_trips is not None:
            # differentiable path: snapshot the initial carried values so
            # while_grad can replay the loop (the loop writes carried vars
            # in place, destroying their pre-loop values)
            snaps = []
            for n in carried:
                v = parent.var(n)
                # unique per loop: two Whiles carrying the same var must
                # not share (and overwrite) one snapshot
                snap = parent.create_var(
                    name=unique_name.generate(n + "@WHILE_INIT"),
                    shape=v.shape, dtype=v.dtype)
                parent.append_op("assign", inputs={"X": [n]},
                                 outputs={"Out": [snap.name]}, attrs={})
                snaps.append(snap.name)
            inputs["InitSnapshot"] = snaps
            attrs["max_trip_count"] = int(max_trips)
        parent.append_op("while", inputs=inputs,
                         outputs={"Out": list(carried)}, attrs=attrs)
        return False


def create_array(dtype, max_len=128):
    """TensorArray analog (ref LoDTensorArray / control_flow.create_array).

    Under XLA the array is a pre-sized dense buffer ``[max_len, ...]`` plus a
    length scalar, materialized lazily at the first ``array_write`` — so it
    composes with While (the buffer is just another carried var).  The buffer
    must receive its first write *outside* any While block so the loop body
    sees an initialized carry.
    """
    helper = LayerHelper("create_array")
    arr = helper.create_variable_for_type_inference(dtype, True)
    ln = helper.create_variable_for_type_inference("int32", True)
    arr.array_len_var = ln.name
    arr.array_max_len = max_len
    arr.is_tensor_array = True
    arr.array_written = False
    return arr


def array_write(x, i, array=None):
    """ref tensor_array_read_write.cc WriteToArray — functional
    dynamic_update_slice on the dense buffer."""
    if array is None:
        array = create_array(x.dtype)
    helper = LayerHelper("array_write")
    inputs = {"X": [x], "I": [i]}
    if getattr(array, "array_written", True):
        inputs["Array"] = [array]
        inputs["ArrayLen"] = [array.array_len_var]
    helper.append_op("array_write", inputs=inputs,
                     outputs={"Out": [array],
                              "OutLen": [array.array_len_var]},
                     attrs={"max_len": getattr(array, "array_max_len", 128)})
    array.array_written = True
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("array_read", inputs={"Array": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("array_length",
                     inputs={"ArrayLen": [array.array_len_var]},
                     outputs={"Out": [out]})
    return out


class Switch:
    """ref control_flow.py Switch — first-true-case-wins piecewise execution.

    Each ``case`` body runs under a ``conditional_block`` whose predicate is
    ``cond AND NOT any-earlier-cond``; ``default()`` fires when no case did.
    Bodies assign into pre-existing parent vars (the reference's usage, e.g.
    LR scheduling), which become the conditional block's outputs.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.program = default_main_program()
        self.pre_not_taken = None   # bool var: no earlier case taken

    def case(self, condition):
        return _SwitchCaseGuard(self, condition)

    def default(self):
        return _SwitchCaseGuard(self, None)


class _SwitchCaseGuard:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        from . import nn
        sw = self.switch
        if self.condition is None:          # default: no earlier case taken
            if sw.pre_not_taken is None:
                raise ValueError("Switch.default() before any case")
            self.pred = sw.pre_not_taken
        elif sw.pre_not_taken is None:      # first case
            self.pred = self.condition
            sw.pre_not_taken = nn.logical_not(self.condition)
        else:
            self.pred = nn.logical_and(sw.pre_not_taken, self.condition)
            sw.pre_not_taken = nn.logical_and(
                sw.pre_not_taken, nn.logical_not(self.condition))
        self.block = self.switch.program._create_block()
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        program = self.switch.program
        inner = program.current_block()
        program._rollback()
        parent = program.current_block()
        reads, writes = _collect_io(inner)
        written = sorted(n for n in writes if parent.has_var(n))
        parent.append_op(
            "conditional_block",
            # reads declared so an enclosing While's capture analysis sees
            # through this case body
            inputs={"Cond": [self.pred.name],
                    "X": sorted(n for n in reads if parent.has_var(n))},
            outputs={"Out": written},
            attrs={"sub_block": inner})
        return False


class _parent_block:
    """Temporarily redirect layer building to the current block's parent
    (used by StaticRNN/DynamicRNN to build init/index vars outside the
    step sub-block)."""

    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.saved = self.program._current_block_idx
        self.program._current_block_idx = \
            self.program.current_block().parent_idx
        return self

    def __exit__(self, *a):
        self.program._current_block_idx = self.saved
        return False


class IfElse:
    """Batch-row conditional (ref control_flow.py IfElse over
    split_lod_tensor/merge_lod_tensor).

    The reference physically partitions the batch by a bool column and runs
    each branch on its rows.  Under XLA (static shapes) both branches run on
    the FULL batch and the outputs merge row-wise by the condition — the
    standard dense re-expression; identical results for row-independent
    branch bodies, which is what the partitioning model supports anyway.
    """

    OUT_IF_ELSE_BLOCKS = True

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._true_outs = []
        self._false_outs = []
        self._in_true = None        # None = outside any branch guard

    def input(self, x):
        """In the reference this slices the branch's rows; dense: identity."""
        return x

    def true_block(self):
        return _IfElseBranch(self, True)

    def false_block(self):
        return _IfElseBranch(self, False)

    def output(self, *outs):
        if self._in_true is None:
            raise ValueError(
                "IfElse.output() must be called inside true_block()/"
                "false_block()")
        if self._in_true:
            self._true_outs.extend(outs)
        else:
            self._false_outs.extend(outs)

    def __call__(self):
        if not self._true_outs or not self._false_outs:
            raise ValueError("IfElse: both branches must call output()")
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError("IfElse: branch output arity mismatch")
        from . import nn, tensor
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            helper = LayerHelper("ifelse_merge")
            out = helper.create_variable_for_type_inference(t.dtype)
            helper.append_op("ifelse_merge",
                             inputs={"Cond": [self.cond], "X": [t],
                                     "Y": [f]},
                             outputs={"Out": [out]})
            merged.append(out)
        return merged if len(merged) > 1 else merged[0]


class _IfElseBranch:
    def __init__(self, ie, is_true):
        self.ie = ie
        self.is_true = is_true

    def __enter__(self):
        self.ie._in_true = self.is_true
        return self

    def __exit__(self, *a):
        self.ie._in_true = None
        return False


class StaticRNN:
    """Time-major static recurrence → one lax.scan (ref control_flow.py
    StaticRNN / operators/recurrent_op.cc).

    Usage mirrors the reference: ``with rnn.step():`` then ``step_input``,
    ``memory``, ``update_memory``, ``step_output``; call ``rnn()`` for the
    stacked outputs.  Inputs are time-major ``[T, batch, ...]``.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.program = default_main_program()
        self.seq_inputs = []      # (parent var, in-block var)
        self.memories = []        # (init parent var, in-block var, new name)
        self.step_outputs = []
        self._time_major = True
        self._block = None

    def step(self):
        return _StaticRNNGuard(self)

    def step_input(self, x):
        block = self.program.current_block()
        if x.shape is None:
            step_shape = None
        elif self._time_major:
            step_shape = list(x.shape[1:])          # scan over axis 0
        else:
            # batch-major: the per-step slice keeps the batch dim
            step_shape = [x.shape[0]] + list(x.shape[2:])
        v = block.create_var(
            name=self.helper.name + ".t_" + str(len(self.seq_inputs)),
            shape=step_shape, dtype=x.dtype)
        self.seq_inputs.append((x, v))
        return v

    def _resolve_batch_ref(self, batch_ref, ref_batch_dim_idx):
        """Map a batch_ref var to one usable from the PARENT block.

        The boot memory is built in the parent block, but callers naturally
        pass in-block vars (the step_input result, per the reference's own
        example, control_flow.py:408).  Step vars map back to their parent
        sequence (batch axis 1 time-major, 0 otherwise); other in-block vars
        fall back to any parent sequence (step inputs share the batch dim);
        a var that is neither visible in the parent nor mappable is a
        build-time error instead of a far-away trace-time KeyError.
        """
        seq_dim = 1 if self._time_major else 0
        for x, v in self.seq_inputs:
            if v.name == batch_ref.name:
                return x, seq_dim
        inner = self.program.current_block()
        if inner.parent is not None and inner.parent.has_var(batch_ref.name):
            return batch_ref, ref_batch_dim_idx
        if self.seq_inputs:
            return self.seq_inputs[0][0], seq_dim
        raise ValueError(
            f"memory(batch_ref={batch_ref.name!r}): var is only defined "
            "inside the rnn step block and no step_input exists yet to take "
            "the batch size from; call step_input first or pass a "
            "parent-block var")

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               dtype="float32", init_value=None, init_batch_dim_idx=0,
               ref_batch_dim_idx=1):
        from . import tensor
        if init is None:
            if shape is None:
                raise ValueError("StaticRNN.memory needs init or shape")
            fill = value if init_value is None else init_value
            if batch_ref is not None:
                # ref control_flow.py:436: shape[init_batch_dim_idx] is
                # replaced by batch_ref's batch size.
                src, dim_idx = self._resolve_batch_ref(
                    batch_ref, ref_batch_dim_idx)
            # build the init in the PARENT block (we're inside the step
            # sub-block here; static_scan reads Init from the parent env)
            with _parent_block(self.program):
                if batch_ref is not None:
                    init = tensor.fill_constant_batch_size_like(
                        src, shape=list(shape), dtype=dtype, value=fill,
                        input_dim_idx=dim_idx,
                        output_dim_idx=init_batch_dim_idx)
                else:
                    init = tensor.fill_constant(
                        shape=list(shape), dtype=dtype, value=fill)
        block = self.program.current_block()
        v = block.create_var(
            name=self.helper.name + ".mem_" + str(len(self.memories)),
            shape=list(init.shape) if init.shape else None, dtype=init.dtype)
        self.memories.append([init, v, None])
        return v

    def update_memory(self, mem, new):
        for m in self.memories:
            if m[1].name == mem.name:
                # write new value back into the memory's own name so the
                # scan body's carry-out reads it (ref rnn_memory_helper)
                block = self.program.current_block()
                block.append_op("assign", inputs={"X": [new.name]},
                                outputs={"Out": [mem.name]}, attrs={})
                m[2] = new.name
                return
        raise ValueError(f"update_memory: {mem.name} is not a memory")

    def step_output(self, o):
        self.step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        outs = self._outs
        return outs if len(outs) > 1 else outs[0]


class _StaticRNNGuard:
    def __init__(self, rnn: StaticRNN):
        self.rnn = rnn

    def __enter__(self):
        self.rnn._block = self.rnn.program._create_block()
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        rnn = self.rnn
        program = rnn.program
        inner = program.current_block()
        program._rollback()
        helper = rnn.helper
        final_vars, out_vars = [], []
        for init, v, new in rnn.memories:
            fv = helper.create_variable_for_type_inference(init.dtype)
            final_vars.append(fv)
        for o in rnn.step_outputs:
            ov = helper.create_variable_for_type_inference(o.dtype)
            out_vars.append(ov)
        parent = program.current_block()
        # captured vars (weights etc.): read in the sub-block, defined in the
        # parent — declared as Params so append_backward sees the dependency
        # and static_scan_grad can produce their grads
        seq_names = {x.name for x, _ in rnn.seq_inputs}
        init_names = {m[0].name for m in rnn.memories}
        inner_names = {v_.name for _, v_ in rnn.seq_inputs} | \
                      {m[1].name for m in rnn.memories}
        read = {n for op_ in inner.ops for n in op_.input_arg_names()}
        written = {n for op_ in inner.ops for n in op_.output_arg_names()}
        params = sorted(n for n in (read - written - inner_names -
                                    seq_names - init_names)
                        if parent.has_var(n))
        parent.append_op(
            "static_scan",
            inputs={"X": [x.name for x, _ in rnn.seq_inputs],
                    "Init": [m[0].name for m in rnn.memories],
                    "Params": params},
            outputs={"FinalStates": [v.name for v in final_vars],
                     "Out": [v.name for v in out_vars]},
            attrs={"sub_block": inner,
                   "state_vars": [m[1].name for m in rnn.memories],
                   "step_input_vars": [v.name for _, v in rnn.seq_inputs],
                   "step_output_vars": [o.name for o in rnn.step_outputs],
                   "time_major": rnn._time_major})
        rnn._outs = out_vars
        rnn._finals = final_vars
        return False


class DynamicRNN(StaticRNN):
    """Batch-major padded recurrence with per-example lengths — the dense
    replacement for the reference's LoD DynamicRNN (control_flow.py:~1700).

    ``step_input(x, seq_len)``: x is ``[batch, T, ...]`` padded; states
    freeze once ``t >= seq_len[b]`` so final states equal the value at each
    sequence's true end (ref's shrink_rnn_memory semantics, done with masks
    instead of batch reordering).
    """

    def __init__(self, name=None):
        super().__init__(name=name)
        self._time_major = False
        self.seq_len = None
        self._t_var = None

    def block(self):
        return self.step()

    def step_input(self, x, seq_len=None):
        if seq_len is None:
            seq_len = getattr(x, "seq_len_var", None)
            if isinstance(seq_len, str):
                blk = self.program.current_block()
                seq_len = blk.var(seq_len) if blk.has_var(seq_len) else None
        if seq_len is not None and self.seq_len is None:
            self.seq_len = seq_len
        # also scan a time-index input for masking: arange [T] -> t scalar
        if self._t_var is None and self.seq_len is not None:
            from . import tensor
            # build [batch, T] index matrix in the parent block so its
            # batch-major slice at step t is the per-row time index t
            with _parent_block(self.program):
                T = x.shape[1]
                steps = tensor.fill_constant_batch_size_like(
                    x, shape=[1, T], dtype="int32", value=0.0)
                helper = LayerHelper("drnn_steps")
                idx = helper.create_variable_for_type_inference("int32", True)
                helper.append_op("drnn_iota", inputs={"X": [steps]},
                                 outputs={"Out": [idx]}, attrs={})
            self._steps_parent = idx
            self._t_var = super().step_input(idx)
        return super().step_input(x)

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               dtype="float32", init_value=None, need_reorder=False):
        if init is None and shape is not None and batch_ref is not None:
            # ref DynamicRNN.memory: shape excludes batch (prepend the slot
            # the boot fill replaces); parent vars are batch-major here
            return super().memory(shape=[1] + list(shape),
                                  batch_ref=batch_ref, value=value,
                                  dtype=dtype, init_value=init_value,
                                  init_batch_dim_idx=0, ref_batch_dim_idx=0)
        return super().memory(init=init, shape=shape, dtype=dtype,
                              value=value, init_value=init_value)

    def update_memory(self, mem, new):
        if self.seq_len is not None and self._t_var is not None:
            from . import nn, tensor
            from .sequence import sequence_mask  # noqa
            helper = LayerHelper("drnn_mask")
            masked = helper.create_variable_for_type_inference(new.dtype)
            helper.append_op(
                "drnn_masked_update",
                inputs={"T": [self._t_var], "SeqLen": [self.seq_len],
                        "New": [new], "Prev": [mem]},
                outputs={"Out": [masked]}, attrs={})
            new = masked
        super().update_memory(mem, new)

    def output(self, *outputs):
        super().output(*outputs)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """ref control_flow.py Print → print op (jax.debug.print at runtime)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": (message or input.name) + " = "})
    return out
