"""Control-flow layers (ref ``python/paddle/fluid/layers/control_flow.py``).

Comparison helpers plus ``increment``/``array`` utilities.  Structured loops
(While/StaticRNN/DynamicRNN) lower to ``lax.while_loop``/``lax.scan`` — see
``paddle_tpu.ops.control_flow_ops``.  Note the TPU-semantics difference the
reference doesn't have: loop bodies are traced once and must be
shape-static; reverse-mode grads flow through ``StaticRNN``/``DynamicRNN``
(scan) but not ``While`` (while_loop), matching JAX.
"""

from __future__ import annotations

from ..framework.core import Variable, default_main_program
from ..layer_helper import LayerHelper
from . import tensor


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


class While:
    """``while cond: body`` over a sub-block → lax.while_loop.

    ref control_flow.py While / operators/controlflow/while_op.cc:43.
    Forward-only (lax.while_loop is not reverse-differentiable); use
    StaticRNN/DynamicRNN (scan) for differentiable recurrence.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.program = default_main_program()
        self.helper = LayerHelper("while", name=name)

    def block(self):
        return _WhileBlockGuard(self)


class _WhileBlockGuard:
    def __init__(self, while_op: While):
        self.while_op = while_op

    def __enter__(self):
        self.block = self.while_op.program._create_block()
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        program = self.while_op.program
        inner = program.current_block()
        program._rollback()
        parent = program.current_block()
        # loop-carried vars: every var read in the sub-block that lives in the
        # parent and is written in the sub-block, plus the condition var.
        written = set()
        read = set()
        for op in inner.ops:
            for n in op.input_arg_names():
                read.add(n)
            for n in op.output_arg_names():
                written.add(n)
        carried = sorted((read | written) & set(parent.vars) | {self.while_op.cond_var.name})
        parent.append_op(
            "while",
            inputs={"Condition": [self.while_op.cond_var.name],
                    "X": sorted(read & set(parent.vars))},
            outputs={"Out": list(carried)},
            attrs={"sub_block": inner, "carried_vars": list(carried)})
        return False


def array_write(x, i, array=None):
    raise NotImplementedError(
        "LoDTensorArray is replaced by lax.scan carries; use StaticRNN "
        "(paddle_tpu.layers.rnn) or Python lists of Variables")


def array_read(array, i):
    raise NotImplementedError(
        "LoDTensorArray is replaced by lax.scan carries; use StaticRNN "
        "(paddle_tpu.layers.rnn) or Python lists of Variables")


def array_length(array):
    raise NotImplementedError("see array_write")


def create_array(dtype):
    raise NotImplementedError("see array_write")


class Switch:
    """ref control_flow.py Switch — piecewise select built from masks."""

    def __init__(self, name=None):
        self.cases = []
        self.default_assigns = None

    def case(self, condition):
        raise NotImplementedError(
            "Switch: use layers.piecewise arithmetic-mask selects "
            "(see learning_rate_scheduler.piecewise_decay) — data-dependent "
            "host control flow does not exist under XLA tracing")

    def default(self):
        return self.case(None)
