"""Tensor-creation layers (ref ``python/paddle/fluid/layers/tensor.py``)."""

from __future__ import annotations

import numpy as np

from ..framework.core import Variable, convert_dtype, default_main_program
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter")
    from ..param_attr import ParamAttr
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(shape=list(shape), dtype=dtype,
                                        name=name, persistable=persistable)
    from ..framework.core import default_startup_program
    sb = default_startup_program().global_block()
    sb.create_var(name=var.name, shape=list(shape), dtype=dtype,
                  persistable=persistable)
    sb.append_op("fill_constant", outputs={"Out": [var.name]},
                 attrs={"shape": list(shape), "dtype": dtype,
                        "value": float(value)})
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(str(arr.dtype))
        helper.append_op("assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(arr.shape),
                                "dtype": str(arr.dtype),
                                "values": arr.reshape(-1).tolist()})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    out.stop_gradient = True
    helper.append_op("fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": convert_dtype(dtype),
                            "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    out.stop_gradient = True
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": convert_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, True)
    ids = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("argsort", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_any_like", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"value": 1.0})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op("reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": list(axis)})
    return out


def has_inf(x):
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return logical_not_out(out)


def _logical_not(x):
    helper = LayerHelper("logical_not")
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


logical_not_out = _logical_not


def has_nan(x):
    return has_inf(x)


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype), True)
    attrs = {"dtype": convert_dtype(dtype)}
    inputs = {}
    for nm, v in (("Start", start), ("End", end), ("Step", step)):
        if isinstance(v, Variable):
            inputs[nm] = [v]
        else:
            attrs[nm.lower()] = v
    helper.append_op("range", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def linspace(start, stop, num, dtype):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype), True)
    if not isinstance(start, Variable):
        start = fill_constant([1], dtype, start)
    if not isinstance(stop, Variable):
        stop = fill_constant([1], dtype, stop)
    if not isinstance(num, Variable):
        num = fill_constant([1], "int32", num)
    helper.append_op("linspace",
                     inputs={"Start": [start], "Stop": [stop], "Num": [num]},
                     outputs={"Out": [out]},
                     attrs={"dtype": convert_dtype(dtype)})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype, True)
    helper.append_op("diag", inputs={"Diagonal": [diagonal]},
                     outputs={"Out": [out]})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    out = helper.create_variable_for_type_inference(convert_dtype(dtype), True)
    helper.append_op("eye", outputs={"Out": [out]},
                     attrs={"num_rows": num_rows,
                            "num_columns": num_columns or num_rows,
                            "dtype": convert_dtype(dtype)})
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """ref tensor_array_to_tensor_op.cc — stack/concat the dense array
    buffer (rows past the written length are zero-padding; mask by
    array_length as with any padded batch)."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_index = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("tensor_array_to_tensor",
                     inputs={"Array": [input]},
                     outputs={"Out": [out], "OutIndex": [out_index]},
                     attrs={"axis": axis, "use_stack": use_stack})
    return out, out_index
