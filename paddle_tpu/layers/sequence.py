"""Sequence layers — the TPU-native replacement for LoD `sequence_ops`.

The reference carries ragged batches as LoDTensors and provides 48
`operators/sequence_ops/` kernels.  On TPU (static shapes!) sequences are
dense padded tensors ``[batch, max_len, ...]`` with an explicit per-example
length vector (SURVEY §5.7) — each layer here takes/propagates that length
companion where the reference would read LoD offsets.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper


def sequence_mask(x, maxlen=None, dtype="int64"):
    """lengths [b] → mask [b, maxlen] (ref sequence_ops/sequence_mask_op)."""
    helper = LayerHelper("sequence_mask")
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"maxlen": maxlen or -1, "out_dtype": dtype})
    return out


def sequence_pool(input, pool_type, is_test=False, seq_len=None):
    """padded [b, t, ...] + lengths → pooled [b, ...]
    (ref sequence_ops/sequence_pool_op.cc; pool_type in
    average/sum/sqrt/max/last/first)."""
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int32", True)
    inputs = {"X": [input]}
    seq_len = seq_len or getattr(input, "seq_len_var", None)
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op("sequence_pool", inputs=inputs,
                     outputs={"Out": [out], "MaxIndex": [idx]},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len=seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len=seq_len)


def sequence_softmax(input, use_cudnn=False, name=None, seq_len=None):
    """masked softmax over the time axis (ref sequence_softmax_op.cc)."""
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input]}
    seq_len = seq_len or getattr(input, "seq_len_var", None)
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op("sequence_softmax", inputs=inputs,
                     outputs={"Out": [out]})
    return out


def sequence_reverse(x, name=None, seq_len=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    seq_len = seq_len or getattr(x, "seq_len_var", None)
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op("sequence_reverse", inputs=inputs,
                     outputs={"Y": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    """Broadcast per-sequence rows of x across y's time dim (padded form)."""
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_expand", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"ref_level": ref_level})
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Identity in padded representation; returns (x, lengths)."""
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64", True)
    inputs = {"X": [x], "PadValue": [pad_value]}
    seq_len = getattr(x, "seq_len_var", None)
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op("sequence_pad", inputs=inputs,
                     outputs={"Out": [out], "Length": [length]},
                     attrs={"padded_length": maxlen or -1})
    return out, length


def sequence_unpad(x, length, name=None):
    """Attach a length companion; data stays padded (zeros beyond length)."""
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_unpad", inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    out.seq_len_var = length.name if hasattr(length, "name") else length
    return out


def sequence_concat(input, name=None):
    """Concat along time axis (padded)."""
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sequence_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("sequence_enumerate", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_expand_as", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": new_dim})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    """ref layers/nn.py sequence_conv → sequence_conv op (dense [b,t,d])."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[filter_size * d, num_filters],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_conv",
                     inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]},
                     attrs={"contextLength": filter_size,
                            "contextStride": filter_stride,
                            "contextStart": -(filter_size // 2)})
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)
