"""Input layers (ref ``python/paddle/fluid/layers/io.py``): ``data`` declares
a feed Variable.  The reference's py_reader/double_buffer pipeline is
reimplemented TPU-style in ``paddle_tpu.data.dataloader`` (host→device
prefetch thread ≈ ``operators/reader/buffered_reader.cc``)."""

from __future__ import annotations

from ..framework.core import default_main_program


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    """ref layers/io.py data — declares a fed variable.

    ``append_batch_size=True`` prepends a batch dim, which we leave symbolic
    (-1) in metadata; the executor specializes on the first fed batch shape
    (XLA shape-keyed jit cache), so vary batch size sparingly.
    ``lod_level`` is accepted for API parity; ragged data is carried as a
    dense padded tensor plus an explicit length companion (SURVEY §5.7).
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block()
    var = block.create_var(name=name, shape=shape, dtype=dtype,
                           stop_gradient=stop_gradient)
    var.is_data = True
    var.lod_level = lod_level
    return var
