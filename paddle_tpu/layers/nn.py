"""The layer DSL: Python functions appending ops to the default main program.

ref ``python/paddle/fluid/layers/nn.py`` (14.4k LoC, 187 exports — ``fc`` at
:231 is the canonical pattern: LayerHelper → create params → append ops →
bias → activation).  Signatures follow the reference so user code ports
unchanged; all compute lowers through the XLA block compiler.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..framework.core import Variable, convert_dtype
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from .math_ops import _elementwise_binary, scale  # re-export


# ---------------------------------------------------------------------------
# dense / embedding
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """ref layers/nn.py:231 — mul(+sum) + elementwise_add + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    dtype = inputs[0].dtype
    mul_results = []
    pattrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    for inp, pa in zip(inputs, pattrs):
        in_dim = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(pa, shape=[in_dim, size], dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op("mul", inputs={"X": [inp], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """ref layers/nn.py embedding → lookup_table op."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op("lookup_table", inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [out]},
                     attrs={"padding_idx": pad, "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": float(alpha)})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------

def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """ref layers/nn.py conv2d → conv2d op + bias + act."""
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fs = _pair(filter_size)
    filter_shape = [num_filters, num_channels // groups] + fs
    import math
    std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
    from ..initializer import NormalInitializer
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype,
                                default_initializer=NormalInitializer(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d", inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation), "groups": groups,
                            "data_format": data_format})
    if bias_attr is False:
        pre_act = pre_bias
    else:
        b = helper.create_parameter(bias_attr, shape=[num_filters],
                                    dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [pre_bias], "Y": [b]},
                         outputs={"Out": [pre_act]}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input, act=act,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    groups = groups or 1
    in_c = input.shape[1]
    if filter_size is None:
        # derive from output_size (ref conv2d_transpose filter inference)
        h = input.shape[2]
        osz = _pair(output_size)
        st, pd = _pair(stride), _pair(padding)
        filter_size = [osz[0] - (h - 1) * st[0] + 2 * pd[0],
                       osz[1] - (input.shape[3] - 1) * st[1] + 2 * pd[1]]
    fs = _pair(filter_size)
    w = helper.create_parameter(param_attr,
                                shape=[in_c, num_filters // groups] + fs,
                                dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation), "groups": groups})
    if bias_attr is False:
        pre_act = pre_bias
    else:
        b = helper.create_parameter(bias_attr, shape=[num_filters],
                                    dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": [pre_bias], "Y": [b]},
                         outputs={"Out": [pre_act]}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type,
                            "ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode, "exclusive": exclusive,
                            "data_format": data_format})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type,
                            "ksize": _pair(pool_size), "strides": [1, 1],
                            "paddings": [0, 0], "adaptive": True})
    return out


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """ref layers/nn.py batch_norm → batch_norm op with 4 params."""
    helper = LayerHelper("batch_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    from ..param_attr import ParamAttr
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[c], dtype="float32",
        default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[c], dtype="float32",
        default_initializer=ConstantInitializer(1.0))
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference("float32", True)
    saved_var = helper.create_variable_for_type_inference("float32", True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test, "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", act=act, name=name)
    dtype = input.dtype
    norm_dim = int(np.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=[norm_dim], dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=[norm_dim], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("group_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon, "groups": groups})
    return helper.append_activation(out)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("l2_normalize", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """ref layers/nn.py spectral_norm → spectral_norm op (weight / σ_max
    via power iteration over persistable u/v buffers)."""
    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w = int(np.prod(weight.shape)) // h
    from ..param_attr import ParamAttr
    from ..initializer import NormalInitializer
    u = helper.create_parameter(
        ParamAttr(initializer=NormalInitializer(0.0, 1.0),
                  trainable=False),
        shape=[h], dtype=weight.dtype)
    v = helper.create_parameter(
        ParamAttr(initializer=NormalInitializer(0.0, 1.0),
                  trainable=False),
        shape=[w], dtype=weight.dtype)
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op("spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    helper = LayerHelper("data_norm", act=act, name=name)
    dtype = input.dtype
    c = input.shape[1]
    bsize = helper.create_parameter(
        None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1e4))
    bsum = helper.create_parameter(
        None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    bsqr = helper.create_parameter(
        None, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1e4))
    means = helper.create_variable_for_type_inference(dtype, True)
    scales = helper.create_variable_for_type_inference(dtype, True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("data_norm",
                     inputs={"X": [input], "BatchSize": [bsize],
                             "BatchSum": [bsum], "BatchSquareSum": [bsqr]},
                     outputs={"Y": [out], "Means": [means], "Scales": [scales]},
                     attrs={"epsilon": epsilon})
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# softmax / losses
# ---------------------------------------------------------------------------

def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    sm = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [sm], "Loss": [loss]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis})
    if return_softmax:
        return loss, sm
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy", inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [loss], "Diff": [diff]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    resid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("huber_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [resid]},
                     attrs={"delta": delta})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_loss", inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [out]}, attrs={"epsilon": epsilon})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss", inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]}, attrs={"reduction": reduction})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op("label_smooth", inputs=inputs, outputs={"Out": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op("rank_loss",
                     inputs={"Label": [label], "Left": [left], "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype, True)
    helper.append_op("margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": margin})
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss")
    out = helper.create_variable_for_type_inference(anchor.dtype)
    helper.append_op("npair_loss",
                     inputs={"Anchor": [anchor], "Positive": [positive],
                             "Labels": [labels]},
                     outputs={"Out": [out]}, attrs={"l2_reg": l2_reg})
    return out


def dice_loss(input, label, epsilon=1e-5):
    from . import tensor as T
    label = T.cast(label, input.dtype)
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dims)
    dice_denominator = reduce_sum(input, dim=reduce_dims) + \
        reduce_sum(label, dim=reduce_dims)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return reduce_mean(dice_score)


# ---------------------------------------------------------------------------
# dropout / misc
# ---------------------------------------------------------------------------

def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    """ref layers/nn.py dropout / operators/dropout_op.cc.

    TPU note: the keep mask is drawn as uint8 random bits (one byte per
    element — bit generation is the dominant dropout cost on TPU), so the
    effective drop probability is quantized to multiples of 1/256 (up to
    ~0.2% absolute bias vs the requested rate), and any tiny nonzero
    ``dropout_prob`` drops at least ~0.39% of elements rather than
    silently becoming a no-op.
    """
    import zlib
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    # per-op RNG tag (derived from the unique out name when the user gives
    # no seed): forward and backward fold the same tag into the per-step
    # key and regenerate identical bits, so the mask is never stored.
    # An explicit seed IS the tag — as in the reference's fix_seed path
    # (dropout_op.cc), two ops given the same seed draw the same pattern.
    tag = seed if seed is not None else \
        (zlib.crc32(out.name.encode()) & 0x7FFFFFFF) or 1
    helper.append_op("dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "seed": tag,
                            "dropout_implementation": dropout_implementation})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"depth": depth})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """ref layers/nn.py — persistable int64 step counter incremented per run."""
    helper = LayerHelper("global_step_counter")
    counter = helper.main_program.global_block().create_var(
        name=counter_name or "@STEP_COUNTER@", shape=(), dtype="int64",
        persistable=True, stop_gradient=True)
    from ..framework.core import default_startup_program
    sb = default_startup_program().global_block()
    if not sb.var_local(counter.name):
        sb.create_var(name=counter.name, shape=(), dtype="int64",
                      persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [counter.name]},
                     attrs={"shape": [], "dtype": "int64",
                            "value": float(begin - step)})
    helper.append_op("increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", True)
    inputs = {"X": [input]}
    attrs = {}
    if isinstance(input, Variable) and isinstance(k, Variable):
        inputs["K"] = [k]
    else:
        attrs["k"] = int(k)
    helper.append_op("top_k", inputs=inputs,
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs=attrs)
    return values, indices


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("transpose2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("reshape2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("squeeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("unsqueeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("flatten2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("stack", inputs={"X": xs}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": dim, "sections": []}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "axis": dim, "sections": list(num_or_sections)}
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("split", inputs={"X": [input]}, outputs={"Out": outs},
                     attrs=attrs)
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def strided_slice(input, axes, starts, ends, strides):
    helper = LayerHelper("strided_slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("strided_slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends), "strides": list(strides)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     inputs={"X": [input], "Ids": [index], "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(ref.dtype)
    helper.append_op("scatter_nd_add",
                     inputs={"X": [ref], "Index": [index], "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value),
                            "data_format": data_format})
    return out


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    helper.append_op(op_type, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"dim": dim, "keep_dim": keep_dim,
                            "reduce_all": dim is None})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_any", input, dim, keep_dim, name)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# elementwise wrappers (ref layers/nn.py elementwise_* exports)
# ---------------------------------------------------------------------------

def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_add", axis=axis, act=act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_sub", axis=axis, act=act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_mul", axis=axis, act=act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_div", axis=axis, act=act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_max", axis=axis, act=act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_min", axis=axis, act=act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_pow", axis=axis, act=act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_mod", axis=axis, act=act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_floordiv", axis=axis, act=act)


# simple unary layer wrappers -------------------------------------------------

def _unary(op_type, x, name=None, **attrs):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def relu(x, name=None): return _unary("relu", x, name)
def sigmoid(x, name=None): return _unary("sigmoid", x, name)
def tanh(x, name=None): return _unary("tanh", x, name)
def exp(x, name=None): return _unary("exp", x, name)
def log(x, name=None): return _unary("log", x, name)
def sqrt(x, name=None): return _unary("sqrt", x, name)
def rsqrt(x, name=None): return _unary("rsqrt", x, name)
def square(x, name=None): return _unary("square", x, name)
def abs(x, name=None): return _unary("abs", x, name)
def ceil(x, name=None): return _unary("ceil", x, name)
def floor(x, name=None): return _unary("floor", x, name)
def cos(x, name=None): return _unary("cos", x, name)
def sin(x, name=None): return _unary("sin", x, name)
def round(x, name=None): return _unary("round", x, name)
def reciprocal(x, name=None): return _unary("reciprocal", x, name)
def softplus(x, name=None): return _unary("softplus", x, name)
def softsign(x, name=None): return _unary("softsign", x, name)
def logsigmoid(x, name=None): return _unary("logsigmoid", x, name)
def sign(x, name=None): return _unary("sign", x, name)
def erf(x, name=None): return _unary("erf", x, name)
def gelu(x, approximate=False, name=None):
    return _unary("gelu", x, name, approximate=approximate)
def leaky_relu(x, alpha=0.02, name=None):
    return _unary("leaky_relu", x, name, alpha=alpha)
def elu(x, alpha=1.0, name=None): return _unary("elu", x, name, alpha=alpha)
def relu6(x, threshold=6.0, name=None):
    return _unary("relu6", x, name, threshold=threshold)
def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None: attrs["scale"] = scale
    if alpha is not None: attrs["alpha"] = alpha
    return _unary("selu", x, name, **attrs)
def pow(x, factor=1.0, name=None): return _unary("pow", x, name, factor=factor)
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary("stanh", x, name, scale_a=scale_a, scale_b=scale_b)
def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _unary("hard_sigmoid", x, name, slope=slope, offset=offset)
def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _unary("hard_swish", x, name, threshold=threshold, scale=scale,
                  offset=offset)
def swish(x, beta=1.0, name=None): return _unary("swish", x, name, beta=beta)
def soft_relu(x, threshold=40.0, name=None):
    return _unary("soft_relu", x, name, threshold=threshold)
def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _unary("brelu", x, name, t_min=t_min, t_max=t_max)
def thresholded_relu(x, threshold=1.0, name=None):
    return _unary("thresholded_relu", x, name, threshold=threshold)
def maxout(x, groups, name=None): return _unary("maxout", x, name, groups=groups)
def logical_not(x, out=None, name=None): return _unary("logical_not", x, name)


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = [int(np.prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def clip(x, min, max, name=None):
    return _unary("clip", x, name, min=float(min), max=float(max))


def clip_by_norm(x, max_norm, name=None):
    return _unary("clip_by_norm", x, name, max_norm=float(max_norm))


def _binary_logical(op_type, x, y, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    return _binary_logical("logical_and", x, y, name)


def logical_or(x, y, out=None, name=None):
    return _binary_logical("logical_or", x, y, name)


def logical_xor(x, y, out=None, name=None):
    return _binary_logical("logical_xor", x, y, name)


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def rank(input):
    return len(input.shape)


def size(input):
    helper = LayerHelper("size")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("size", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype, "min": min,
                            "max": max, "seed": seed})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    helper.append_op("uniform_random", outputs={"Out": [out]},
                     attrs={"shape": shape, "dtype": dtype, "min": min,
                            "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": mean, "std": std,
                            "seed": seed, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    helper.append_op("gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": shape, "mean": mean, "std": std,
                            "seed": seed, "dtype": dtype})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """Shadowed by layers.structured.sampling_id (the package export);
    kept for direct ``layers.nn`` imports."""
    from .structured import sampling_id as _impl
    return _impl(x, min=min, max=max, seed=seed, dtype=dtype)


def sums(input, out=None):
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    helper = LayerHelper("image_resize", name=name)
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
          "TRILINEAR": "trilinear_interp"}[resample]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(op, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"out_h": out_shape[0], "out_w": out_shape[1],
                            "align_corners": align_corners,
                            "align_mode": align_mode})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1):
    helper = LayerHelper("resize_trilinear", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("trilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_d": out_shape[0], "out_h": out_shape[1],
                            "out_w": out_shape[2],
                            "align_corners": align_corners})
    return out


def pixel_shuffle(x, upscale_factor):
    return _unary("pixel_shuffle", x, None, upscale_factor=upscale_factor)


def space_to_depth(x, blocksize, name=None):
    return _unary("space_to_depth", x, name, blocksize=blocksize)


def shuffle_channel(x, group, name=None):
    return _unary("shuffle_channel", x, name, group=group)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _unary("temporal_shift", x, name, seg_num=seg_num,
                  shift_ratio=shift_ratio)


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    helper = LayerHelper("affine_channel", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout})
    return helper.append_activation(out)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    s = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    d = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    helper.append_op("unfold", inputs={"X": [x]}, outputs={"Y": [out]},
                     attrs={"kernel_sizes": list(k), "strides": list(s),
                            "paddings": list(p), "dilations": list(d)})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    k = _pair(filter_size)
    s = _pair(stride)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    helper.append_op("im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": k, "strides": s, "paddings": list(p)})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(param_attr,
                                shape=[size, x.shape[1], y.shape[1]],
                                dtype=dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[1, size], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    block_q=None, block_k=None, name=None):
    """Fused online-softmax attention over [b, h, T, d] tensors.

    TPU-native replacement for the matmul→softmax→matmul chain of the
    reference Transformer recipe (ref dist_transformer.py:1034
    scaled_dot_product_attention) — Pallas kernel on TPU, O(T) memory.
    block_q/block_k default to the kernel's tuned sizes (512/1024 capped
    at T — the v5e-measured optimum).
    """
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op("flash_attention", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"causal": causal, "sm_scale": sm_scale or 0.0,
                            "block_q": block_q or 0, "block_k": block_k or 0})
    return out


def ring_attention(q, k, v, causal=False, sm_scale=None, axis_name="sp",
                   name=None):
    """Sequence-parallel attention: KV shards rotate over the mesh's
    ``sp`` axis (paddle_tpu.pallas.ring_attention); degrades to
    flash_attention when no sp axis is active.  The long-context
    capability the reference lacks (SURVEY §5.7)."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op("ring_attention", inputs={"Q": [q], "K": [k], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"causal": causal, "sm_scale": sm_scale or 0.0,
                            "axis_name": axis_name})
    return out


# ---------------------------------------------------------------------------
# similarity / losses / misc wrappers (ref layers/nn.py assorted exports)
# ---------------------------------------------------------------------------

def cos_sim(X, Y):
    """ref layers/nn.py cos_sim → cos_sim op."""
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking loss (ref bpr_loss_op.cc)."""
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("bpr_loss", inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """ref layers/nn.py center_loss → center_loss op w/ centers parameter."""
    helper = LayerHelper("center_loss", param_attr=param_attr)
    dtype = input.dtype
    from ..param_attr import ParamAttr
    if param_attr is None:
        # centers are updated by the op itself, not by the optimizer
        param_attr = ParamAttr(trainable=False)
    centers = helper.create_parameter(param_attr,
                                      shape=[num_classes, input.shape[1]],
                                      dtype=dtype,
                                      default_initializer=ConstantInitializer(0.0))
    centers.stop_gradient = True
    from .tensor import fill_constant
    lr = fill_constant(shape=[1], dtype=dtype, value=float(alpha))
    loss = helper.create_variable_for_type_inference(dtype)
    sample_centers = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [lr]},
        outputs={"Loss": [loss], "SampleCenterDiff": [sample_centers],
                 "CentersOut": [centers]},
        attrs={"cluster_num": num_classes, "need_update": update_center})
    return loss


def multiplex(inputs, index):
    """Row-wise select across candidate tensors (ref multiplex_op.cc)."""
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex", inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def where(condition):
    """Indices of true elements, padded to static shape (ref where_op /
    where_index)."""
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("where", inputs={"Condition": [condition]},
                     outputs={"Out": [out]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (ref crop_op.cc); shape/offsets are python lists."""
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    if shape is None:
        # the build-time batch dim is -1; "crop to own shape" = identity crop
        shape = [s for s in x.shape]
    shape = [x.shape[i] if s == -1 and i > 0 else s
             for i, s in enumerate(shape)]
    helper.append_op("crop", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "offsets": list(offsets or [0] * len(x.shape))})
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    """ref crop_tensor_op.cc — static-shape variant under XLA."""
    helper = LayerHelper("crop_tensor", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("crop_tensor", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape or []),
                            "offsets": list(offsets or [0] * len(x.shape))})
    return out


def random_crop(x, shape, seed=None):
    """ref random_crop_op.cc — crop trailing dims to `shape` at random."""
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("random_crop", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return out


def mean_iou(input, label, num_classes):
    """ref mean_iou_op.cc: per-batch mean IoU + per-class wrong/correct."""
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32", True)
    wrong = helper.create_variable_for_type_inference("int32", True)
    correct = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes})
    return miou, wrong, correct


def unique(x, dtype="int32"):
    """ref unique_op.cc (padded to static size under XLA)."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype, True)
    index = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index]})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype, True)
    index = helper.create_variable_for_type_inference(dtype, True)
    count = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count]})
    return out, index, count


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """ref shard_index_op.cc — map global ids to shard-local ids."""
    helper = LayerHelper("shard_index")
    out = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("shard_index", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"index_num": index_num, "nshards": nshards,
                            "shard_id": shard_id,
                            "ignore_value": ignore_value})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op("pad_constant_like", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"pad_value": float(pad_value)})
    return out


def scatter_nd(index, updates, shape, name=None):
    """ref layers/nn.py scatter_nd — scatter_nd_add onto zeros."""
    helper = LayerHelper("scatter_nd", name=name)
    out = helper.create_variable_for_type_inference(updates.dtype)
    helper.append_op("scatter_nd",
                     inputs={"Index": [index], "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """ref hash_op.cc — num_hash hashed id columns mod hash_size."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("hash", inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"num_hash": num_hash, "mod_by": hash_size})
    return out


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("similarity_focus", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def add_position_encoding(input, alpha, beta, name=None):
    """ref add_position_encoding_op.cc — sinusoidal position encoding."""
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("add_position_encoding", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"alpha": float(alpha), "beta": float(beta)})
    return out


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (ref fsp_op.cc)."""
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": float(soft_max_up_bound),
                            "soft_max_lower_bound": float(soft_max_lower_bound)})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution (ref tree_conv_op.cc)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = nodes_vector.dtype
    feature_size = nodes_vector.shape[2]
    w = helper.create_parameter(param_attr,
                                shape=[feature_size, 3, output_size,
                                       num_filters],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("tree_conv",
                     inputs={"NodesVector": [nodes_vector],
                             "EdgeSet": [edge_set], "Filter": [w]},
                     outputs={"Out": [out]},
                     attrs={"max_depth": max_depth})
    if bias_attr is not False:
        out = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(out)


def merge_selected_rows(x, name=None):
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, name=None):
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("get_tensor_from_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (ref row_conv_op.cc)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("row_conv", inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op via jax.pure_callback (ref py_func_op.cc).

    ``out`` vars must be pre-created with concrete shapes
    (``create_variable`` style); a leading -1 is bound to the batch size at
    trace time.  ``backward_func(*x, *out, *out_grads) -> x_grads`` enables
    reverse-mode through the callback.
    """
    from ..ops.control_flow_ops import PY_FUNC_TABLE
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    fid = len(PY_FUNC_TABLE)
    PY_FUNC_TABLE[fid] = {"forward": func, "backward": backward_func}
    helper.append_op("py_func", inputs={"X": list(xs)},
                     outputs={"Out": list(outs)},
                     attrs={"func_id": fid,
                            "out_shapes": [list(o.shape) for o in outs],
                            "out_dtypes": [o.dtype for o in outs]})
    return out


def fake_quantize_abs_max(x, bit_length=8):
    """ref operators/fake_quantize_op.cc (QAT building block)."""
    helper = LayerHelper("fake_quantize_abs_max")
    out = helper.create_variable_for_type_inference(x.dtype)
    scale = helper.create_variable_for_type_inference("float32")
    helper.append_op("fake_quantize_abs_max", inputs={"X": [x]},
                     outputs={"Out": [out], "OutScale": [scale]},
                     attrs={"bit_length": bit_length})
    return out


def fake_quantize_dequantize_abs_max(x, bit_length=8):
    """Fused quant-dequant with STE grad (QAT workhorse)."""
    helper = LayerHelper("fake_quantize_dequantize_abs_max")
    out = helper.create_variable_for_type_inference(x.dtype)
    scale = helper.create_variable_for_type_inference("float32")
    helper.append_op("fake_quantize_dequantize_abs_max",
                     inputs={"X": [x]},
                     outputs={"Out": [out], "OutScale": [scale]},
                     attrs={"bit_length": bit_length})
    return out


def fused_lm_head_ce(x, size, label, param_attr=None, bias_attr=None,
                     ignore_index=-100, chunk_size=1024):
    """Chunked LM-head + cross-entropy: O(chunk × vocab) memory instead of
    materializing [tokens, vocab] logits (TPU-native; no fluid analog).
    Owns its projection parameters like ``fc`` (same weight orientation
    [d_in, size])."""
    helper = LayerHelper("fused_lm_head_ce", param_attr=param_attr,
                         bias_attr=bias_attr)
    d_in = int(x.shape[-1])
    w = helper.create_parameter(param_attr, shape=[d_in, size],
                                dtype=x.dtype)
    inputs = {"X": [x], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[size], dtype=x.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "fused_lm_head_ce", inputs=inputs, outputs={"Loss": [loss]},
        attrs={"ignore_index": ignore_index, "chunk_size": chunk_size})
    return loss


def switch_moe_ffn(x, num_experts, d_inner, capacity_factor=1.25,
                   act="relu", param_prefix="moe", name=None):
    """Switch-Transformer mixture-of-experts FFN over [b, t, d] input.

    Returns (out, aux_loss).  Expert weights carry dist_spec ("ep", ...)
    so a mesh with an ``ep`` axis shards the experts (GSPMD inserts the
    dispatch/combine all-to-alls); on an ep-less mesh the annotations are
    inert and the layer runs dense.  No reference counterpart — TPU-native
    capability behind parallel/mesh.py's ``ep`` axis.
    """
    helper = LayerHelper("switch_ffn", name=name)
    d = int(x.shape[-1])
    E, F = int(num_experts), int(d_inner)

    def _p(suffix, shape, ep_spec, is_bias=False):
        from ..param_attr import ParamAttr
        v = helper.create_parameter(
            ParamAttr(name=f"{param_prefix}.{suffix}"), shape, x.dtype,
            is_bias=is_bias)
        v.dist_spec = ep_spec
        return v

    gate_w = _p("gate.w", [d, E], None)
    w1 = _p("w1", [E, d, F], ("ep", None, None))
    b1 = _p("b1", [E, F], ("ep", None), is_bias=True)
    w2 = _p("w2", [E, F, d], ("ep", None, None))
    b2 = _p("b2", [E, d], ("ep", None), is_bias=True)

    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "switch_ffn",
        inputs={"X": [x], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"capacity_factor": float(capacity_factor), "act": act})
    return out, aux
